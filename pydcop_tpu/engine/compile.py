"""Compile a DCOP factor graph into dense, padded, bucketed device arrays.

This is the bridge between the host-side problem model and the jitted
engine.  The layout decisions are what make the kernels MXU/VPU friendly
and the sharding communication-minimal:

- **Arity buckets.** Factors are grouped by arity; each bucket stacks its
  cost hypercubes into one `[F, Dmax, ..., Dmax]` tensor so the
  factor→variable min-reduction is a single batched reduction per bucket
  (reference analogue: the O(d^arity) python enumeration in maxsum's
  factor_costs_for_var, pydcop/algorithms/maxsum.py:382).

- **Messages live in bucket space** as `[F, arity, Dmax]` arrays — the
  slot (f, p) holds the message on the edge between factor f and the
  variable at position p of its scope.  "Sending" is writing a row; there
  is no queue and no serialization (reference analogue: the Messaging
  priority queue, pydcop/infrastructure/communication.py:500).
  Variable-side aggregation is a segment-sum over `var_ids`; when buckets
  are sharded over a mesh axis this is the *only* cross-device op (one
  all-reduce of the [V, D] totals per superstep, riding ICI).

- **Domain padding.** All domains are padded to Dmax with `BIG` cost so
  padded slots never win a min-reduction; `var_valid` masks them out of
  normalizations and argmins.  For `objective=max` problems costs are
  negated at compile time and the final cost re-negated on the host, so
  kernels only ever minimize.

- **Device padding.** Bucket rows are padded to a multiple of `pad_to`
  (the mesh size); padding rows have zero cost and point at a sentinel
  variable row (index V) which is dropped after aggregation, so sharded
  runs need no ragged handling.

- **Zero-ary constraints** are folded into a host-side constant offset
  (`meta.constant_cost`).

Example (compile a 2-variable problem and inspect the device layout)::

    >>> from pydcop_tpu.dcop.dcop import DCOP
    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> from pydcop_tpu.dcop.relations import constraint_from_str
    >>> from pydcop_tpu.engine.compile import compile_dcop
    >>> d = Domain('d', '', [0, 1])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> dcop = DCOP('doc', objective='min')
    >>> dcop.add_constraint(constraint_from_str('c', 'x * y', [x, y]))
    >>> graph, meta = compile_dcop(dcop)
    >>> graph.var_costs.shape        # V+1 sentinel row, Dmax slots
    (3, 2)
    >>> [b.costs.shape for b in graph.buckets]  # one binary bucket
    [(1, 2, 2)]
    >>> meta.var_names
    ('x', 'y')
"""

import os
import threading
from collections import OrderedDict
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Variable, _stable_noise
from pydcop_tpu.dcop.relations import Constraint, NAryFunctionRelation
from pydcop_tpu.observability.metrics import registry as metrics_registry
from pydcop_tpu.observability.trace import tracer

BIG = np.float32(1e9)


class CompileCache:
    """Process-wide structure-keyed layout cache.

    Re-solving a same-*shaped* problem (new cost tables, same
    variables/scopes — the repeated-traffic serving pattern the
    ROADMAP targets) should not pay layout construction again: the
    padded ``var_ids`` arrays and the aggregation indexing
    (``agg_perm``/``agg_sorted_seg``/``agg_starts``/``agg_ends``/
    ``agg_ell`` — an argsort + searchsorted + list fill over all E
    edges) are pure functions of the graph *structure* (variable
    count, per-factor scope indices, pad_to, aggregation), never of
    the costs.  ``compile_factor_graph`` keys them here; a hit skips
    layout and agg-array construction entirely (``layout_builds``
    counts the builds, so tests can assert the skip).  Cached arrays
    are frozen (``writeable=False``) — every consumer treats compiled
    graphs as immutable (the engines ``device_put`` them; decimation
    copies before clamping).

    Bounded LRU; ``PYDCOP_COMPILE_CACHE=0`` disables globally.
    Thread-safe: the solve service compiles on concurrent submitter
    threads (serving/service.py), so get/put must not race the LRU
    bookkeeping (an unlocked ``move_to_end`` can KeyError against a
    concurrent eviction).
    """

    def __init__(self, maxsize: int = 8):
        self.maxsize = maxsize
        self._entries: "OrderedDict" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.layout_builds = 0

    def get(self, key):
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return entry
            self.misses += 1
            return None

    def put(self, key, entry):
        with self._lock:
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.maxsize:
                self._entries.popitem(last=False)

    def clear(self):
        with self._lock:
            self._entries.clear()
            self.hits = self.misses = self.layout_builds = 0

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "layout_builds": self.layout_builds,
                "entries": len(self._entries),
            }


compile_cache = CompileCache()


def _freeze(arr: Optional[np.ndarray]) -> Optional[np.ndarray]:
    if arr is not None:
        arr.flags.writeable = False
    return arr


class FactorBucket(NamedTuple):
    """All factors of one arity, stacked."""

    costs: np.ndarray    # [F, Dmax]*arity, f32, BIG on padded slots
    var_ids: np.ndarray  # [F, arity] int32 (sentinel V on padding rows)

    @property
    def arity(self) -> int:
        return self.var_ids.shape[1]

    @property
    def n_factors(self) -> int:
        return self.var_ids.shape[0]


class CompiledFactorGraph(NamedTuple):
    """Device-ready dense form of a factor graph.

    Array members are numpy on the host; the runner moves them to device
    (optionally sharded).

    The optional ``agg_*`` arrays select the variable-aggregation
    strategy for the MaxSum superstep (see ops/maxsum.aggregate_beliefs
    and benchmarks/exp_aggregation.py for the measured decision):

    - all None (default): unsorted scatter-add (``segment_sum``);
    - perm + sorted_seg: compile-time edge sort, per-cycle gather into
      sorted order, ``segment_sum(indices_are_sorted=True)``;
    - perm + starts/ends: edge sort + cumsum + per-variable boundary
      gathers — no scatter at all (HBM-regime candidate);
    - ell: per-variable edge lists padded to the maximum degree
      ([V+1, K] indices into the flat edge order; dummy slots hold E,
      one past the last edge — the kernel clips the index and masks
      the contribution to zero) — the aggregation becomes a dense
      gather + K-way sum with no
      scatter and no sort, the layout XLA/TPU vectorizes best
      (scatter-add on TPU serializes row updates; measured on-chip
      round 5: 4.9 ms/iteration for 900k scattered rows at 100k
      vars, ~5.5 ns/row).

    Sharded graphs always use the scatter path (a global edge sort
    would turn the local gather into a cross-device one), so
    ``shard_graph`` drops these arrays.
    """

    var_costs: np.ndarray   # [V+1, Dmax] f32 (last row = sentinel)
    var_valid: np.ndarray   # [V+1, Dmax] bool
    buckets: Tuple[FactorBucket, ...]
    agg_perm: Optional[np.ndarray] = None        # [E] int32
    agg_sorted_seg: Optional[np.ndarray] = None  # [E] int32 (sorted)
    agg_starts: Optional[np.ndarray] = None      # [V+1] int32
    agg_ends: Optional[np.ndarray] = None        # [V+1] int32
    agg_ell: Optional[np.ndarray] = None         # [V+1, K] int32

    @property
    def n_vars(self) -> int:
        return self.var_costs.shape[0] - 1

    @property
    def dmax(self) -> int:
        return self.var_costs.shape[1]


class FactorGraphMeta(NamedTuple):
    """Host-side metadata to map device results back to the problem."""

    var_names: Tuple[str, ...]
    domains: Tuple[Tuple, ...]          # domain values per var
    factor_names: Tuple[str, ...]       # bucket order, real factors only
    bucket_sizes: Tuple[int, ...]       # real (unpadded) factors per bucket
    mode: str                           # 'min' or 'max'
    constant_cost: float = 0.0          # folded zero-ary constraints
    # [V, Dmax] sign-adjusted variable costs WITHOUT tie-breaking
    # noise (zeros on domain padding) — what DCOP.solution_cost
    # charges for variable-side costs; used by cost traces.
    var_base_costs: Optional[np.ndarray] = None

    def assignment_from_indices(self, idx: Sequence[int]) -> Dict:
        return {
            name: self.domains[i][int(idx[i])]
            for i, name in enumerate(self.var_names)
        }


def _round_up(n: int, multiple: int) -> int:
    if multiple <= 1:
        return n
    return ((n + multiple - 1) // multiple) * multiple


AGGREGATIONS = ("scatter", "sorted", "boundary", "ell")
AUTO_AGGREGATION = "auto"

# Branch-and-bound message pruning (ops/maxsum.prune_tables): the
# compacted factor->variable reduction gathers at most ``prune_width``
# surviving rows per factor — a STATIC width, so the pruned program
# keeps the bucketed layout's fixed shapes (the structure cache and
# every aggregation strategy see the same arrays).  max(2, min(8,
# D//8)) balances the reduction saving (the fast path's work scales
# with the budget) against how often the data-dependent survivor
# count fits it; below PRUNE_MIN_DOMAIN the dense reduction is
# already cheaper than the bound bookkeeping, so pruning compiles to
# the dense path there.
PRUNE_WIDTH_DIVISOR = 8
PRUNE_WIDTH_CAP = 8
PRUNE_MIN_DOMAIN = 8


def prune_width(dmax: int) -> int:
    """Static surviving-row budget of the pruned binary-factor update.
    Capped: the compacted reduction's work grows with the budget, and
    measured survivor counts at the fixpoint sit at 1-5 across every
    problem family tried — a budget past 8 only dilutes the win."""
    return max(2, min(PRUNE_WIDTH_CAP, dmax // PRUNE_WIDTH_DIVISOR))

# Placeholder costs array for layout-only FactorBucket shims — the
# aggregation builder reads only var_ids.
_EMPTY_COSTS = np.zeros((0,), np.float32)


def validated_aggregation(params: dict, pad_to: int) -> str:
    """Resolve an algorithm's ``aggregation`` param against the mesh
    size.  shard_graph rebuilds graphs WITHOUT the agg_* arrays (and
    the partitioned engine aggregates per shard with local scatter),
    so a non-scatter strategy on a mesh would silently measure
    scatter — refuse loudly instead (one policy for every algorithm
    family).

    ``"auto"`` resolves to ``"scatter"`` on a mesh (the only valid
    sharded strategy — not an error, auto means "pick a valid one for
    me") and passes through otherwise; the caller is expected to run
    the measured selection (engine/autotune.autotune_aggregation) on
    the compiled graph."""
    aggregation = params.get("aggregation", "scatter")
    if aggregation == AUTO_AGGREGATION:
        return "scatter" if pad_to > 1 else AUTO_AGGREGATION
    if pad_to > 1 and aggregation != "scatter":
        raise ValueError(
            f"aggregation={aggregation!r} is single-device; sharded "
            "runs always use the scatter path (engine/sharding."
            "shard_graph drops the aggregation arrays)")
    return aggregation


def build_aggregation_arrays(buckets: Sequence[FactorBucket],
                             n_segments: int, aggregation: str):
    """Compile-time edge indexing for the non-scatter aggregation paths.

    Edges are the flattened (bucket, factor, position) slots in bucket
    order — the same order ``aggregate_beliefs`` flattens messages in.
    Returns the 5 ``agg_*`` field values for CompiledFactorGraph:
    (perm, sorted_seg, starts, ends, ell).
    """
    if aggregation == "scatter":
        return None, None, None, None, None
    if aggregation not in AGGREGATIONS:
        raise ValueError(
            f"aggregation must be one of {AGGREGATIONS}, "
            f"got {aggregation!r}"
        )
    seg = np.concatenate(
        [b.var_ids.reshape(-1) for b in buckets]
    ) if buckets else np.zeros((0,), np.int32)
    perm = np.argsort(seg, kind="stable").astype(np.int32)
    sorted_seg = seg[perm].astype(np.int32)
    if aggregation == "sorted":
        return perm, sorted_seg, None, None, None
    starts = np.searchsorted(
        sorted_seg, np.arange(n_segments), side="left"
    ).astype(np.int32)
    ends = np.searchsorted(
        sorted_seg, np.arange(n_segments), side="right"
    ).astype(np.int32)
    if aggregation == "boundary":
        return perm, None, starts, ends, None
    # ell: [V+1, K] edge indices per variable, K = max REAL-variable
    # degree (the sentinel row V absorbs every padding-edge slot and
    # would otherwise inflate K; its sum is dropped by the kernel, so
    # its list stays all-dummy).  Dummy slots hold E — the kernel
    # clips the index and masks the contribution to zero.
    n_edges = seg.size
    deg = ends - starts
    k_max = int(deg[:-1].max()) if n_segments > 1 and n_edges else 1
    k_max = max(k_max, 1)
    # Hub guard: K is the MAX degree, so one power-law hub inflates
    # every variable's padded list ([V+1, K] int32 — a 1M-var graph
    # with a degree-10k hub would allocate 40 GB).  Refuse with
    # guidance instead of OOMing the device.
    ell_bytes = n_segments * k_max * 4
    if ell_bytes > 2 << 30:
        raise ValueError(
            f"aggregation='ell' would allocate a {n_segments} x "
            f"{k_max} edge-list array ({ell_bytes / (1 << 30):.1f} "
            "GiB): the max variable degree is far above the mean "
            f"({n_edges / max(n_segments - 1, 1):.1f}) — use "
            "aggregation='scatter' for hub-dominated graphs")
    ell = np.full((n_segments, k_max), n_edges, np.int32)
    # Position of each sorted edge within its variable's list.
    k_pos = np.arange(n_edges) - starts[sorted_seg]
    real = sorted_seg < (n_segments - 1)
    ell[sorted_seg[real], k_pos[real]] = perm[real]
    return None, None, None, None, ell


def _factor_table(c: Constraint, sign: float, dtype,
                  memo: Dict, vectorize: bool) -> np.ndarray:
    """Sign-adjusted dense table for one factor, memoized on the
    structural table signature: factors whose expressions differ only
    in variable names (every generated-edge family) evaluate ONCE per
    bucket instead of once per factor, and each evaluation is the
    vectorized numpy path (relations.NAryFunctionRelation.to_array)
    instead of a d^arity python loop.  ``vectorize=False`` restores
    the per-factor per-assignment reference path — the A/B baseline
    ``make perf-smoke`` measures against."""
    if not vectorize:
        if isinstance(c, NAryFunctionRelation):
            # The pre-vectorization behavior: the base per-assignment
            # enumeration loop.
            return sign * np.asarray(
                Constraint.to_array(c), dtype=dtype)
        return sign * np.asarray(c.to_array(), dtype=dtype)
    sig = c.table_signature()
    if sig is not None:
        table = memo.get(sig)
        if table is not None:
            return table
    table = sign * np.asarray(c.to_array(), dtype=dtype)
    if sig is not None:
        memo[sig] = table
    return table


def compile_factor_graph(
    variables: Sequence[Variable],
    constraints: Sequence[Constraint],
    mode: str = "min",
    noise_level: float = 0.0,
    noise_seed: Optional[int] = None,
    pad_to: int = 1,
    dtype=np.float32,
    aggregation: str = "scatter",
    vectorize: bool = True,
    use_cache: Optional[bool] = None,
) -> Tuple[CompiledFactorGraph, FactorGraphMeta]:
    """Build the dense arrays.  `noise_level` adds deterministic
    per-variable-value noise (maxsum's tie-breaking noise, reference
    maxsum.py:477-487, seeded here for reproducibility).

    ``vectorize`` enables the batched numpy cost-table evaluation
    plus the per-bucket table memo (see :func:`_factor_table`);
    ``use_cache`` controls the structure-keyed layout cache
    (:class:`CompileCache`; default on, ``PYDCOP_COMPILE_CACHE=0``
    disables process-wide)."""
    if use_cache is None:
        use_cache = os.environ.get("PYDCOP_COMPILE_CACHE") != "0"
    # Materialize before measuring: callers may pass iterators, which
    # have no len() (the body always listified them).
    variables = list(variables)
    constraints = list(constraints)
    # tracer.span is its own no-op when disabled; compile is a cold
    # path, so the kwargs build costs nothing worth guarding.
    with tracer.span("compile_graph", "engine",
                     n_vars=len(variables),
                     n_constraints=len(constraints)):
        return _compile_factor_graph(
            variables, constraints, mode, noise_level, noise_seed,
            pad_to, dtype, aggregation, vectorize, use_cache,
        )


def _compile_factor_graph(variables, constraints, mode, noise_level,
                          noise_seed, pad_to, dtype, aggregation,
                          vectorize, use_cache):
    variables = list(variables)
    constraints = list(constraints)
    var_index = {v.name: i for i, v in enumerate(variables)}
    for c in constraints:
        for v in c.dimensions:
            if v.name not in var_index:
                raise ValueError(
                    f"Constraint {c.name} references variable {v.name} "
                    "which has no computation node — external (read-"
                    "only) variables require the 'maxsum_dynamic' "
                    "algorithm, which slices them out before compiling"
                )
    v_count = len(variables)
    dmax = max((len(v.domain) for v in variables), default=1)
    sign = 1.0 if mode == "min" else -1.0

    # Variable cost table (+ sentinel row for padding edges).
    var_costs = np.full((v_count + 1, dmax), BIG, dtype=dtype)
    var_valid = np.zeros((v_count + 1, dmax), dtype=bool)
    var_base = np.zeros((v_count, dmax), dtype=dtype)
    for i, v in enumerate(variables):
        d = len(v.domain)
        costs = sign * v.cost_vector()[:d]
        var_base[i, :d] = costs
        if noise_level:
            costs = costs + _stable_noise(v.name, d, noise_level, noise_seed)
        var_costs[i, :d] = costs
        var_valid[i, :d] = True

    constant_cost = 0.0
    by_arity: Dict[int, List[Constraint]] = {}
    for c in constraints:
        if c.arity == 0:
            constant_cost += float(c())
            continue
        by_arity.setdefault(c.arity, []).append(c)

    # Per-factor scope indices, one [n_facs, arity] array per arity.
    # Needed both for the bucket layout and as the structure-cache
    # key: the layout (padded var_ids + agg_* arrays) is a pure
    # function of these indices + (v_count, pad_to, aggregation).
    arities = sorted(by_arity)
    scope_ids: Dict[int, np.ndarray] = {}
    for arity in arities:
        facs = by_arity[arity]
        scope_ids[arity] = np.array(
            [[var_index[v.name] for v in c.dimensions] for c in facs],
            dtype=np.int32,
        ).reshape(len(facs), arity)

    layout = None
    cache_key = None
    if use_cache:
        cache_key = (
            v_count, pad_to, aggregation,
            tuple((a, scope_ids[a].tobytes()) for a in arities),
        )
        layout = compile_cache.get(cache_key)
        # registry.active gate, like every optional series this PR
        # adds: an unobserved solve must not accumulate samples that
        # a later observed solve's .prom dump would misattribute.
        if metrics_registry.active:
            metrics_registry.counter(
                "pydcop_compile_cache_total",
                "Structure-cache lookups by outcome",
            ).inc(outcome="hit" if layout is not None else "miss")
    if layout is None:
        compile_cache.layout_builds += 1
        if metrics_registry.active:
            metrics_registry.counter(
                "pydcop_layout_builds_total",
                "Factor-graph layout constructions (cache misses + "
                "uncached compiles)",
            ).inc()
        var_ids_by_arity = {}
        for arity in arities:
            n_facs = scope_ids[arity].shape[0]
            n_rows = _round_up(n_facs, pad_to)
            ids = np.full((n_rows, arity), v_count, dtype=np.int32)
            ids[:n_facs] = scope_ids[arity]
            var_ids_by_arity[arity] = _freeze(ids)
        agg = build_aggregation_arrays(
            [FactorBucket(_EMPTY_COSTS, ids)
             for ids in var_ids_by_arity.values()],
            v_count + 1, aggregation,
        )
        layout = (var_ids_by_arity, tuple(_freeze(a) for a in agg))
        if use_cache:
            compile_cache.put(cache_key, layout)
    var_ids_by_arity, (perm, sorted_seg, starts, ends, ell) = layout

    buckets = []
    factor_names: List[str] = []
    bucket_sizes: List[int] = []
    for arity in arities:
        facs = by_arity[arity]
        n_rows = var_ids_by_arity[arity].shape[0]
        shape = (n_rows,) + (dmax,) * arity
        costs = np.full(shape, BIG, dtype=dtype)
        memo: Dict = {}
        for fi, c in enumerate(facs):
            factor_names.append(c.name)
            table = _factor_table(c, sign, dtype, memo, vectorize)
            idx = tuple(slice(0, s) for s in table.shape)
            costs[(fi,) + idx] = table
        # Padding rows keep cost 0 and the sentinel variable.
        costs[len(facs):] = 0.0
        buckets.append(FactorBucket(costs, var_ids_by_arity[arity]))
        bucket_sizes.append(len(facs))
    compiled = CompiledFactorGraph(
        var_costs=var_costs,
        var_valid=var_valid,
        buckets=tuple(buckets),
        agg_perm=perm,
        agg_sorted_seg=sorted_seg,
        agg_starts=starts,
        agg_ends=ends,
        agg_ell=ell,
    )
    meta = FactorGraphMeta(
        var_names=tuple(v.name for v in variables),
        domains=tuple(tuple(v.domain) for v in variables),
        factor_names=tuple(factor_names),
        bucket_sizes=tuple(bucket_sizes),
        mode=mode,
        constant_cost=constant_cost,
        var_base_costs=var_base,
    )
    return compiled, meta


def compile_dcop(dcop: DCOP, noise_level: float = 0.0,
                 noise_seed: Optional[int] = None, pad_to: int = 1,
                 aggregation: str = "scatter",
                 vectorize: bool = True,
                 use_cache: Optional[bool] = None,
                 ) -> Tuple[CompiledFactorGraph, FactorGraphMeta]:
    return compile_factor_graph(
        list(dcop.variables.values()),
        list(dcop.constraints.values()),
        mode=dcop.objective,
        noise_level=noise_level,
        noise_seed=noise_seed,
        pad_to=pad_to,
        aggregation=aggregation,
        vectorize=vectorize,
        use_cache=use_cache,
    )

"""``pydcop replica_dist``: offline replica placement.

Reference parity: pydcop/commands/replica_dist.py — compute where k
replicas of each computation would be placed (the same distributed UCS
used by ``pydcop run``), without solving the DCOP.  Output is YAML:

    replica_dist:
      <computation>: [agent, agent, ...]
"""

import json

from pydcop_tpu.commands._utils import build_algo_def


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "replica_dist", help="compute an offline replica placement")
    parser.add_argument("dcop_files", nargs="+", help="dcop yaml file(s)")
    parser.add_argument("-a", "--algo", required=True,
                        help="algorithm (for computation footprints)")
    parser.add_argument("-d", "--distribution", default="oneagent",
                        help="distribution method or file")
    parser.add_argument("-k", "--ktarget", type=int, required=True,
                        help="number of replicas per computation")
    parser.add_argument("--replication",
                        default="dist_ucs_hostingcosts",
                        choices=["dist_ucs_hostingcosts"],
                        help="replication algorithm (reference "
                             "parity; hosting-cost UCS is the only "
                             "complete one the reference ships)")
    parser.add_argument("-m", "--mode", default="thread",
                        choices=["thread", "process"],
                        help="run the placement protocol on agent "
                             "threads or one OS process per agent")
    parser.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    from pydcop_tpu.algorithms import load_algorithm_module
    from pydcop_tpu.computations_graph import load_graph_module
    from pydcop_tpu.dcop.yamldcop import load_dcop_from_file
    from pydcop_tpu.infrastructure.run import (
        PROCESS_READY_TIMEOUT,
        THREAD_READY_TIMEOUT,
        _build_distribution,
        run_local_process_dcop,
        run_local_thread_dcop,
    )

    dcop = load_dcop_from_file(args.dcop_files)
    algo_def = build_algo_def(args.algo, None, dcop.objective)
    algo_module = load_algorithm_module(algo_def.algo)
    cg = load_graph_module(
        algo_module.GRAPH_TYPE).build_computation_graph(dcop)
    distribution = _build_distribution(
        dcop, cg, algo_module, args.distribution
    )
    # args.replication is argparse-constrained to the single
    # implemented algorithm (the runners hardwire the hosting-cost UCS
    # computation); when a second algorithm lands, thread the choice
    # through run_local_*_dcop -> OrchestratedAgent here.
    runner = (run_local_process_dcop if args.mode == "process"
              else run_local_thread_dcop)
    orchestrator = runner(
        algo_def, cg, distribution, dcop, replication=True
    )
    try:
        if not orchestrator.wait_ready(
                PROCESS_READY_TIMEOUT if args.mode == "process"
                else THREAD_READY_TIMEOUT):
            print("Error: agents did not become ready")
            return 3
        orchestrator.deploy_computations()
        timeout = args.timeout if args.timeout is not None else 30.0
        replica_dist = orchestrator.start_replication(
            args.ktarget, timeout=timeout
        )
    finally:
        orchestrator.stop_agents(5)
        orchestrator.stop()

    # Provenance block first (reference replica_dist_format.yml): the
    # parameters that produced this placement, so a placement file is
    # reproducible on its own.
    lines = ["inputs:"]
    lines.append(f"  dcop: {json.dumps(list(args.dcop_files))}")
    lines.append(f"  graph: {algo_module.GRAPH_TYPE}")
    lines.append(f"  algo: {algo_def.algo}")
    lines.append(f"  distribution: {args.distribution}")
    lines.append(f"  k: {args.ktarget}")
    lines.append(f"  replication: {args.replication}")
    lines.append("replica_dist:")
    for comp in sorted(replica_dist.mapping):
        hosts = replica_dist.mapping[comp]
        lines.append(f"  {comp}: {json.dumps(hosts)}")
    text = "\n".join(lines) + "\n"
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(text)
    print(text)
    return 0

# Test / check targets (reference parity: pydcop Makefile — unit,
# api, cli, doctests, and a static gate; the reference's mypy target
# maps to tools/static_check.py since mypy is not installable here).

PY ?= python

.PHONY: all test chaos chaos-soak chaos-soak-quick trace-demo perf-smoke serve-smoke shard-smoke bench-check unit api cli check doctest bench dryrun onchip

# 0 = the full scenario matrix; `make test` runs the --quick
# device-side gate (chaos_soak.QUICK_GATE; fixed seed, ~20 s).
SOAK_SCENARIOS ?= 0

all: check test

# Executable docstring examples across the package (reference
# Makefile:6 `pytest --doctest-modules ./pydcop`).  Root conftest.py
# forces the CPU backend for the examples.
doctest:
	$(PY) -m pytest --doctest-modules pydcop_tpu -q

# Chaos gate: the resilience battery under a FIXED fault seed (the
# fault pattern is a pure function of seed + edge + message index, so
# a red run reproduces with the same command).  The battery lives in
# tests/, so the default `make test` below already runs it — chaos is
# a gate inside the default suite, and this target is the fast,
# seed-pinned way to run it alone.
chaos:
	PYDCOP_CHAOS_SEED=42 $(PY) -m pytest \
		tests/unit/test_resilience_battery.py -q

# Self-healing gate: the seeded chaos-soak scenario matrix
# (drop+dup+delay / partition-with-heal / silent kill / guard trip /
# checkpoint corruption / serve crash + journal replay / poison bin /
# shard trip + repartition), each asserting the global invariants:
# valid assignment, monotone cycle counter, no orphaned computations,
# and health verdicts consistent with the injected kill schedule.  A
# red scenario prints its seed + trace file for replay
# (tools/chaos_soak.py --only NAME).  Default = full matrix;
# `make test` runs the --quick device-side gate (~20 s).
chaos-soak:
	PYDCOP_CHAOS_SEED=42 $(PY) tools/chaos_soak.py \
		--scenarios $(SOAK_SCENARIOS)

chaos-soak-quick:
	PYDCOP_CHAOS_SEED=42 $(PY) tools/chaos_soak.py --quick

# Observability gate: solve a small graph coloring through the real
# CLI with --trace + --metrics and assert the Chrome trace validates
# (json loads, spans well-nested, expected span kinds), the metrics
# JSONL has a monotone cycle counter, the Prometheus dump parses, and
# `pydcop trace summary` aggregates it.  See tools/trace_demo.py.
trace-demo:
	$(PY) tools/trace_demo.py

# Perf-smoke gate: the hot-path claims measured on CPU — vectorized
# compile >= 3x over the per-factor loop on a 10k-factor expression
# instance, a structure-cache hit skipping layout construction
# (counter-asserted) and compiling faster, the aggregation autotuner
# picking a valid strategy + replaying from its JSON cache, and the
# always-on flight recorder costing <= 5% on the segmented-run
# benchmark.  See tools/perf_smoke.py.
perf-smoke:
	$(PY) tools/perf_smoke.py

# Serve-smoke gate: the solve service end-to-end over real HTTP —
# a mixed-structure burst of N requests must complete in fewer than
# N device dispatches (batch coalescing counter-asserted), every
# response must equal the solo api.solve assignment, and an overload
# burst past the high-water mark must yield clean 429s (never a hang
# or a dropped request) with pydcop_requests_total accounting for
# every request.  See tools/serve_smoke.py + docs/serving.md.
serve-smoke:
	$(PY) tools/serve_smoke.py

# Shard-smoke gate: the partitioned engine on 8 forced host devices —
# a 2k-var loopy grid partitioned with edge_cut_fraction < 0.3,
# per-superstep halo-exchange volume asserted strictly below the
# replicated all-reduce volume, and bit-parity with the unsharded
# solve; plus the shard_graph auto-padding regression.  See
# tools/shard_smoke.py + docs/sharding.md.
shard-smoke:
	$(PY) tools/shard_smoke.py

# Bench regression sentinel: noise-aware (median ± MAD per backend)
# run-over-run check of the BENCH_r*.json trajectory, with a
# sparkline trajectory line per backend.  Hard gate standalone; `make
# test` runs it ADVISORY (`-` prefix: a slow shared host must not
# block an unrelated PR).  See tools/bench_sentinel.py.
bench-check:
	$(PY) tools/bench_sentinel.py

test: trace-demo perf-smoke serve-smoke shard-smoke
	-$(PY) tools/bench_sentinel.py
	$(MAKE) chaos-soak-quick
	$(PY) -m pytest tests/ -q

unit:
	$(PY) -m pytest tests/unit -q

api:
	$(PY) -m pytest tests/api -q

cli:
	$(PY) -m pytest tests/cli -q

check: doctest
	$(PY) tools/static_check.py

bench:
	$(PY) bench.py

dryrun:
	$(PY) -c "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)"

# Probe the TPU tunnel in a bounded loop; the moment it answers, run
# the queued hardware decision list unattended (headline bench,
# aggregation A/B, collective share, layout A/B) and append results to
# BENCH_TPU.md.  Probe history goes to BENCH_TPU_PROBELOG.jsonl either
# way.  See tools/onchip_autopilot.py.
onchip:
	$(PY) tools/onchip_autopilot.py

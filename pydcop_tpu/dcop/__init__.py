"""DCOP problem modeling: domains, variables, constraints, agents, YAML IO.

Reference parity: pydcop/dcop/ (objects.py, relations.py, dcop.py,
yamldcop.py, scenario.py).
"""

from pydcop_tpu.dcop.objects import (  # noqa: F401
    AgentDef,
    BinaryVariable,
    Domain,
    ExternalVariable,
    Variable,
    VariableDomain,
    VariableNoisyCostFunc,
    VariableWithCostDict,
    VariableWithCostFunc,
    create_agents,
    create_binary_variables,
    create_variables,
)
from pydcop_tpu.dcop.dcop import DCOP  # noqa: F401

"""``pydcop replica_dist`` — placeholder, implemented later this round.

Reference parity target: pydcop/commands/replica_dist.py.
"""


def set_parser(subparsers):
    parser = subparsers.add_parser("replica_dist", help="replica_dist (not yet implemented)")
    parser.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    print("pydcop replica_dist: not implemented yet in pydcop-tpu")
    return 3

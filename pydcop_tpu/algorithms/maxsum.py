"""MaxSum: synchronous belief-propagation on the factor graph.

Reference parity: pydcop/algorithms/maxsum.py — the north-star hot loop.
Parameters (:212-220): damping 0.5, damping_nodes both, stability 0.1,
noise 0.01, start_messages leafs.  Message semantics are implemented in
pydcop_tpu.ops.maxsum (batched) and, for agent mode, in
pydcop_tpu.infrastructure computations built from `build_computation`.

Device-path note: the batched BSP engine fires *all* factors and
variables each cycle, which corresponds to ``start_messages=all``
semantics; `start_messages` only changes the transient, not the fixed
point, and is accepted for compatibility.  Send-suppression after
SAME_COUNT identical messages (reference :106) is a wire-traffic
optimization with no effect on message *content*; on device, messages
are array rows and the optimization is moot.

Example (doctest, runs on the CPU backend under ``make doctest``)::

    >>> from pydcop_tpu.api import solve
    >>> from pydcop_tpu.dcop.dcop import DCOP
    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> from pydcop_tpu.dcop.relations import constraint_from_str
    >>> d = Domain('d', '', [0, 1])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> dcop = DCOP('doc', objective='min')
    >>> dcop.add_constraint(constraint_from_str('c', '(x + y - 1)**2', [x, y]))
    >>> res = solve(dcop, 'maxsum', max_cycles=50)
    >>> round(res['cost'], 3)
    0.0
"""

import time
from functools import partial
from typing import Optional

from pydcop_tpu.algorithms import AlgoParameterDef, AlgorithmDef
from pydcop_tpu.computations_graph import factor_graph as fg
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.engine.compile import compile_dcop, validated_aggregation
from pydcop_tpu.engine.runner import DeviceRunResult, MaxSumEngine

GRAPH_TYPE = "factor_graph"

# Partitioned sharding (api.solve(shards=N)): this module builds the
# ShardedMaxSumEngine; amaxsum and maxsum_dynamic delegate their
# device path here and re-declare the flag.
SUPPORTS_SHARDS = True

HEADER_SIZE = 0
UNIT_SIZE = 1
# Messages considered identical after this many resends (agent mode).
SAME_COUNT = 4
STABILITY_COEFF = 0.1

algo_params = [
    AlgoParameterDef("damping", "float", None, 0.5),
    AlgoParameterDef(
        "damping_nodes", "str", ["vars", "factors", "both", "none"], "both"
    ),
    AlgoParameterDef("stability", "float", None, STABILITY_COEFF),
    AlgoParameterDef("noise", "float", None, 0.01),
    AlgoParameterDef(
        "start_messages", "str", ["leafs", "leafs_vars", "all"], "all"
    ),
    # Device-path extension beyond the reference: decimation
    # (arXiv:1706.02209) — message passing alternating with clamping
    # the most confident variables at segment boundaries, the clamped
    # problem warm-starting from the surviving messages
    # (engine/runner.DecimationPlan).  0 disables (reference
    # behavior); > 0 enables with that fraction (in %) of variables
    # fixed per round.
    AlgoParameterDef("decimation", "int", None, 0),
    # Margin-threshold decimation: clamp ONLY variables whose belief
    # margin (best vs second-best value gap) exceeds this — converged
    # parts of the graph stop paying for message updates while
    # undecided regions keep iterating.  0 disables; combine with
    # decimation:N to cap the per-round clamp fraction.
    AlgoParameterDef("decimation_margin", "float", None, 0.0),
    # Branch-and-bound message pruning (arXiv:1906.06863;
    # ops/maxsum.prune_tables): per-edge running bounds mask dominated
    # hypercube rows out of the binary factor->variable
    # min-aggregation and a compacted reduction does ~D/K of the dense
    # work once the survivors fit the static budget.  Results are
    # IDENTICAL to the unpruned kernel (bit-identical on integer
    # tables — gated in make perf-smoke); wins on large domains
    # (D >= ~32), edge layout only.
    AlgoParameterDef("prune", "bool", None, False),
    # Variable-aggregation strategy for the superstep (device path;
    # see engine/compile.build_aggregation_arrays).  "scatter" is the
    # parity default; "sorted" and "ell" (padded dense-gather edge
    # lists — no scatter at all) are the HBM-regime alternatives
    # measured by benchmarks/exp_aggregation.py.  The fourth strategy
    # there ("boundary", prefix-sum + boundary differences) is
    # experiment-only: f32 prefix sums over millions of edges cancel
    # catastrophically at exactly the scale it targets, and TPUs have
    # no f64 to accumulate in — so it is not offered for solves.
    # Sharded runs always use scatter (shard_graph drops the sort
    # arrays).  "auto" micro-times the strategies on the compiled
    # graph and picks the measured winner (engine/autotune.py;
    # decision + timings land in result metrics, and a JSON shape
    # cache skips the measurement on re-solves).
    AlgoParameterDef(
        "aggregation", "str",
        ["scatter", "sorted", "ell", "auto"], "scatter"
    ),
    # Message-array layout (device path).  "edge" keeps messages as
    # [F, arity, D] (domain minor); "lane" transposes to [D, arity, F]
    # — factors on the TPU lane axis — the HBM-regime candidate
    # measured by benchmarks/exp_layout.py (see ops/maxsum_lane.py).
    # Single-device and scatter-aggregation only.
    AlgoParameterDef("layout", "str", ["edge", "lane"], "edge"),
]


def computation_memory(node) -> float:
    """Footprint: sum of incident message sizes (reference maxsum.py
    :127-171)."""
    return fg.computation_memory(node)


def communication_load(src, target: str) -> float:
    """One cost table per message (reference maxsum.py:174-209)."""
    return fg.communication_load(src, target)


def build_computation(comp_def):
    """Agent-mode computation factory."""
    from pydcop_tpu.infrastructure.computations import build_algo_computation

    return build_algo_computation("maxsum", comp_def)


def _replay_auto_choice(dcop: DCOP):
    """Pre-compile lookup of a persisted autotune decision.

    The shape key is computed from the DCOP directly (variable/domain
    counts, per-arity factor counts, max scope degree — identical to
    the compiled graph's key at pad_to=1, the only case 'auto'
    measures).  On a hit the winner is returned as the aggregation to
    COMPILE WITH, so the layout comes from engine/compile's structure
    cache; on a miss the caller compiles scatter and measures.

    Returns ``(aggregation, agg_info_or_None)``.
    """
    from pydcop_tpu.engine.autotune import cached_choice, dcop_shape_key

    key = dcop_shape_key(dcop)
    choice = cached_choice(key)
    if choice is None:
        return "scatter", None
    return choice, {
        "aggregation": choice,
        "aggregation_source": "cache",
        "aggregation_key": key,
    }


def decimation_plan_from_params(params: dict):
    """Resolve the ``decimation`` / ``decimation_margin`` params into
    an :class:`~pydcop_tpu.engine.runner.DecimationPlan` (None = off).

    ``decimation:N`` alone is the classic schedule — top-N% of free
    variables by belief margin clamped per round until everything is
    fixed.  ``decimation_margin:M`` switches to threshold mode — only
    variables whose margin exceeds M clamp (capped at N% per round
    when both are given; uncapped otherwise), and nothing is forced,
    so an undecided graph keeps message passing untouched."""
    n = int(params.get("decimation", 0) or 0)
    margin = float(params.get("decimation_margin", 0.0) or 0.0)
    if n <= 0 and margin <= 0:
        return None
    from pydcop_tpu.engine.runner import DecimationPlan

    return DecimationPlan(
        margin=margin,
        frac_per_round=(n / 100.0) if n > 0 else 1.0,
        force_progress=margin <= 0,
    )


def build_engine(dcop: DCOP, params: dict, mesh=None,
                 n_devices: Optional[int] = None,
                 shards: Optional[int] = None) -> MaxSumEngine:
    """Compile + construct the engine from validated algo params — the
    single place the parameter->engine wiring lives (solve_on_device
    and the CLI's device-mode trace reconstruction both use it).

    ``aggregation='auto'`` compiles with scatter (the universally
    valid baseline), measures the candidate strategies on the actual
    compiled graph (engine/autotune.py — mesh and hub-guard
    constraints respected there), swaps in the winner's agg arrays,
    and annotates the engine so every result reports the decision.

    ``shards=N`` (N >= 2) selects the PARTITIONED engine instead of
    the replicated-variable mesh: a min-edge-cut partition
    (engine/partition.py) assigns variables and factors to shards,
    each shard owns its local slice of the variable tables, and only
    cut-edge (halo) state crosses devices per superstep — O(cut·D)
    communication instead of the replicated path's O(V·D)
    (engine/sharding.py; docs/sharding.md).  Mutually exclusive with
    ``mesh``/``n_devices``; partition statistics and communication
    accounting land in every result's ``metrics``."""
    if shards is not None and shards > 1:
        if mesh is not None or n_devices:
            raise ValueError(
                "shards= (partitioned engine) and mesh=/n_devices= "
                "(replicated sharding) are mutually exclusive")
        if params.get("layout", "edge") == "lane":
            raise ValueError(
                "layout='lane' is single-device; the partitioned "
                "engine uses the edge layout")
        if decimation_plan_from_params(params) is not None:
            raise ValueError(
                "decimation clamps the single-device var_costs "
                "table; run without shards=")
        # The partitioned superstep aggregates locally with scatter;
        # reuse the mesh aggregation policy (auto -> scatter,
        # anything else refused loudly).
        aggregation = validated_aggregation(params, max(shards, 2))
        from pydcop_tpu.engine.multihost import partitioned_mesh
        from pydcop_tpu.engine.runner import ShardedMaxSumEngine

        graph, meta = compile_dcop(
            dcop, noise_level=params.get("noise", 0.01),
            aggregation=aggregation,
        )
        return ShardedMaxSumEngine(
            graph, meta,
            mesh=partitioned_mesh(shards),
            damping=params.get("damping", 0.5),
            damping_nodes=params.get("damping_nodes", "both"),
            stability=params.get("stability", STABILITY_COEFF),
            prune=bool(params.get("prune", False)),
        )
    pad_to = 1
    if mesh is not None:
        pad_to = mesh.size
    elif n_devices:
        pad_to = n_devices
    aggregation = validated_aggregation(params, pad_to)
    agg_info = None
    if aggregation == "auto":
        # Compile with scatter (the universally valid baseline) and
        # tune on the compiled structure below — unless a persisted
        # decision replays pre-compile (see _replay_auto_choice).
        aggregation = "scatter"
    elif params.get("aggregation") == "auto":
        # validated_aggregation already resolved auto -> scatter for
        # the mesh case; record why nothing was measured.
        agg_info = {"aggregation": "scatter",
                    "aggregation_source": "mesh"}
    if params.get("aggregation") == "auto" and agg_info is None \
            and params.get("layout", "edge") == "lane":
        # The lane layout carries its own scatter aggregation;
        # nothing to tune.
        agg_info = {"aggregation": "scatter",
                    "aggregation_source": "lane"}
    if params.get("aggregation") == "auto" and agg_info is None:
        # Replay a persisted decision BEFORE compiling: the winner
        # then lands in compile_dcop's aggregation argument and its
        # layout arrays come out of the structure cache — a warm
        # auto-solve rebuilds nothing.
        aggregation, agg_info = _replay_auto_choice(dcop)
    graph, meta = compile_dcop(
        dcop, noise_level=params.get("noise", 0.01), pad_to=pad_to,
        aggregation=aggregation,
    )
    if params.get("aggregation") == "auto" and agg_info is None:
        from pydcop_tpu.engine.autotune import (
            apply_aggregation,
            autotune_aggregation,
        )

        agg_info = autotune_aggregation(graph, pad_to=pad_to)
        if agg_info["aggregation"] != "scatter":
            try:
                graph = apply_aggregation(
                    graph, agg_info["aggregation"])
            except ValueError:
                # Builder refusal (e.g. hub guard) on a strategy that
                # nonetheless timed: never fail an 'auto' solve —
                # scatter is always valid.
                agg_info = dict(
                    agg_info, aggregation="scatter",
                    aggregation_source="fallback")
    engine = MaxSumEngine(
        graph, meta,
        damping=params.get("damping", 0.5),
        damping_nodes=params.get("damping_nodes", "both"),
        stability=params.get("stability", STABILITY_COEFF),
        mesh=mesh, n_devices=n_devices,
        layout=params.get("layout", "edge"),
        prune=bool(params.get("prune", False)),
    )
    if agg_info is not None:
        engine.extra_metrics.update(agg_info)
    return engine


def solve_on_device(dcop: DCOP, algo_def: AlgorithmDef,
                    max_cycles: int = 1000, mesh=None,
                    n_devices: Optional[int] = None,
                    shards: Optional[int] = None,
                    stop_on_convergence: bool = True,
                    warmup: bool = False, **_) -> DeviceRunResult:
    """Batched BSP MaxSum on TPU/CPU devices."""
    params = algo_def.params
    engine = build_engine(dcop, params, mesh=mesh,
                          n_devices=n_devices, shards=shards)
    plan = decimation_plan_from_params(params)
    if plan is not None:
        # Decimation is the SEGMENTED mode: clamping happens at the
        # boundaries the engine already syncs on (zero new syncs in
        # the jitted loop), and the clamp set rides snapshots and
        # recovery retains.  warmup is a no-op here: the segmented
        # runner's metrics['cycles_per_s'] already excludes compile
        # time; re-running the whole solve would double wall time for
        # nothing.
        return engine.run_checkpointed(
            max_cycles=max_cycles,
            segment_cycles=plan.cycles_per_round,
            decimation=plan,
        )
    run = partial(
        engine.run, max_cycles=max_cycles,
        stop_on_convergence=stop_on_convergence,
    )
    if warmup:
        # Prime the jit cache so the timed run below is steady-state
        # (each run starts from fresh initial messages, so re-running
        # is side-effect free).
        t0 = time.perf_counter()
        run()
        warm_s = time.perf_counter() - t0
        res = run()
        res.metrics["warmup_time_s"] = warm_s
        return res
    return run()

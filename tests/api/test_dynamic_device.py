"""Device-path dynamic DCOP tests (VERDICT #7).

The DynamicMaxSumEngine must (a) warm-start across run segments with no
behavioral difference vs one long run, (b) absorb factor edits through
padding slack without recompiling, (c) carry messages over a recompile
when an edit outgrows the slack, and (d) keep cost continuity across
events.
"""

import numpy as np
import pytest

from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.engine.dynamic import DynamicMaxSumEngine

D3 = Domain("colors", "color", [0, 1, 2])


def _ring(n=12, seed=0):
    """Ring of n variables with equality-penalty constraints."""
    rng = np.random.default_rng(seed)
    variables = [Variable(f"v{i}", D3) for i in range(n)]
    eq = np.eye(3)
    constraints = [
        NAryMatrixRelation(
            [variables[i], variables[(i + 1) % n]], eq, f"c{i}")
        for i in range(n)
    ]
    return variables, constraints


def test_split_run_equals_single_run():
    variables, constraints = _ring()
    e1 = DynamicMaxSumEngine(variables, constraints, noise_seed=4)
    r1a = e1.run(40, stop_on_convergence=False)
    r1b = e1.run(40, stop_on_convergence=False)
    e2 = DynamicMaxSumEngine(variables, constraints, noise_seed=4)
    r2 = e2.run(80, stop_on_convergence=False)
    assert r1b.cycles == r2.cycles == 80
    assert r1b.assignment == r2.assignment


def test_change_factor_no_recompile():
    variables, constraints = _ring(6)
    eng = DynamicMaxSumEngine(variables, constraints, noise_seed=1)
    res = eng.run(60)
    assert res.metrics["recompiles"] == 0
    base_conflicts = sum(
        res.assignment[f"v{i}"] == res.assignment[f"v{(i + 1) % 6}"]
        for i in range(6)
    )
    assert base_conflicts == 0
    # Flip c0 into an equality PREFERENCE (penalize differing): the
    # fixpoint must adapt so v0 == v1.
    neq = 1.0 - np.eye(3)
    eng.change_factor("c0", NAryMatrixRelation(
        [variables[0], variables[1]], neq, "c0"))
    res2 = eng.run(120)
    assert res2.metrics["recompiles"] == 0  # slack edit, same program
    assert res2.assignment["v0"] == res2.assignment["v1"]
    assert res2.cycles > res.cycles  # warm continuation, not a restart


def test_remove_and_add_factor_within_slack():
    variables, constraints = _ring(8)
    eng = DynamicMaxSumEngine(
        variables, constraints, noise_seed=2, slack=0.5)
    eng.run(40)
    eng.remove_factor("c3")
    assert "c3" not in eng.factors
    eq = np.eye(3)
    # New chord factor fits the freed/slack rows: no recompile.
    eng.add_factor(NAryMatrixRelation(
        [variables[0], variables[4]], eq, "chord"))
    res = eng.run(80)
    assert res.metrics["recompiles"] == 0
    # The chord constraint is active: v0 != v4.
    assert res.assignment["v0"] != res.assignment["v4"]


def test_add_beyond_slack_recompiles_and_warm_starts():
    variables, constraints = _ring(8)
    eng = DynamicMaxSumEngine(
        variables, constraints, noise_seed=3, slack=0.0)
    res0 = eng.run(60)
    cost0 = eng.cost(res0.assignment)
    # slack=0 still keeps >=1 spare row (implementation guarantees
    # n+1); exhaust it, then one more forces a recompile.
    eq = np.eye(3)
    added = 0
    while eng._free[0]:
        i = added + 1
        eng.add_factor(NAryMatrixRelation(
            [variables[0], variables[i + 1]], eq, f"x{added}"))
        added += 1
    eng.add_factor(NAryMatrixRelation(
        [variables[2], variables[6]], eq, "overflow"))
    res1 = eng.run(120)
    assert res1.metrics["recompiles"] >= 1
    # Warm start survived the recompile: the cycle counter continued.
    assert res1.cycles > res0.cycles
    # Cost continuity: the pre-event solution was conflict-free on the
    # surviving constraints; the warm-started run must not regress on
    # them (only the new constraints add requirements).
    cost1 = eng.cost(res1.assignment)
    assert cost1 <= cost0 + 1.0


def test_add_variable_recompiles_and_links():
    variables, constraints = _ring(6)
    eng = DynamicMaxSumEngine(variables, constraints, noise_seed=5)
    eng.run(40)
    w = Variable("w0", D3)
    eq = np.eye(3)
    eng.add_factor(NAryMatrixRelation([variables[0], w], eq, "cw"))
    res = eng.run(120)
    assert "w0" in res.assignment
    assert res.assignment["w0"] != res.assignment["v0"]
    assert res.metrics["recompiles"] >= 1


def test_cost_continuity_across_noop_event():
    """An event that does not change the problem must not perturb the
    trajectory at all: state is identical to just continuing."""
    variables, constraints = _ring(10)
    eng = DynamicMaxSumEngine(variables, constraints, noise_seed=6)
    res_a = eng.run(50, stop_on_convergence=False)
    # remove + re-add the same factor: graph returns to the same math.
    c5 = eng.factors["c5"]
    eng.remove_factor("c5")
    eng.add_factor(c5)
    res_b = eng.run(50, stop_on_convergence=False)
    # The edge messages were reset by the edit, but the surviving state
    # pulls the trajectory back: same conflict-free fixpoint.
    assert eng.cost(res_b.assignment) <= eng.cost(res_a.assignment)


def test_checkpoint_resume_bit_exact(tmp_path):
    """Checkpoint + restore into a FRESH engine continues the
    trajectory exactly: split run across processes-worth of state
    equals one uninterrupted run."""
    variables, constraints = _ring(14, seed=9)
    e1 = DynamicMaxSumEngine(variables, constraints, noise_seed=9)
    e1.run(35, stop_on_convergence=False)
    ckpt = str(tmp_path / "state.npz")
    e1.checkpoint(ckpt)

    v2, c2 = _ring(14, seed=9)
    e2 = DynamicMaxSumEngine(v2, c2, noise_seed=9)
    e2.restore(ckpt)
    resumed = e2.run(35, stop_on_convergence=False)

    e3 = DynamicMaxSumEngine(*_ring(14, seed=9), noise_seed=9)
    single = e3.run(70, stop_on_convergence=False)
    assert resumed.cycles == single.cycles == 70
    assert resumed.assignment == single.assignment


def test_checkpoint_restore_rejects_mismatched_problem(tmp_path):
    variables, constraints = _ring(10, seed=1)
    e1 = DynamicMaxSumEngine(variables, constraints, noise_seed=1)
    e1.run(10, stop_on_convergence=False)
    ckpt = str(tmp_path / "state.npz")
    e1.checkpoint(ckpt)

    other = DynamicMaxSumEngine(*_ring(12, seed=1), noise_seed=1)
    import pytest as _pytest

    with _pytest.raises(ValueError):
        other.restore(ckpt)


def test_checkpoint_requires_a_run(tmp_path):
    eng = DynamicMaxSumEngine(*_ring(6, seed=0))
    import pytest as _pytest

    with _pytest.raises(ValueError, match="never ran"):
        eng.checkpoint(str(tmp_path / "x.npz"))


def test_checkpoint_after_edits_remaps_rows(tmp_path):
    """Dynamic edits reuse freed rows, so a checkpointing engine's row
    layout can differ from a fresh engine's for the same factor set;
    restore must remap message rows by factor name."""
    variables, constraints = _ring(10, seed=2)
    e1 = DynamicMaxSumEngine(
        variables, constraints, noise_seed=2, slack=0.5)
    e1.run(25, stop_on_convergence=False)
    # Remove then re-add c3 with a DIFFERENT table: it lands in a
    # freed/slack row, not its original position.
    neq = 1.0 - np.eye(3)
    e1.remove_factor("c3")
    e1.add_factor(NAryMatrixRelation(
        [variables[3], variables[4]], neq, "c3"))
    e1.run(25, stop_on_convergence=False)
    ckpt = str(tmp_path / "edited.npz")
    e1.checkpoint(ckpt)
    row_in_e1 = e1.slots["c3"]

    # Fresh engine from the FINAL constraint set: c3 sits at its
    # natural build position, which differs from e1's reused row.
    final_constraints = list(e1.factors.values())
    e2 = DynamicMaxSumEngine(
        variables, final_constraints, noise_seed=2, slack=0.5)
    assert e2.slots["c3"] != row_in_e1
    e2.restore(ckpt)
    r2 = e2.run(40, stop_on_convergence=False)
    r1 = e1.run(40, stop_on_convergence=False)
    assert r1.assignment == r2.assignment
    # The re-added preference constraint holds in both.
    assert r2.assignment["v3"] == r2.assignment["v4"]

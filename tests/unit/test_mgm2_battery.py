"""Protocol battery for the agent-mode MGM2 computation
(infrastructure/agent_breakout.Mgm2Computation) — the 5-phase
offer/response/gain/go machine, driven message by message with a
mocked sender (reference test_algorithms_mgm2.py depth).
"""

import random
from unittest.mock import MagicMock

import numpy as np
import pytest

from pydcop_tpu.algorithms import AlgorithmDef, ComputationDef
from pydcop_tpu.computations_graph import constraints_hypergraph as chg
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.infrastructure.agent_breakout import (
    Mgm2Computation,
    Mgm2GainMessage,
    Mgm2GoMessage,
    Mgm2OfferMessage,
    Mgm2ResponseMessage,
    Mgm2ValueMessage,
)

d2 = Domain("d", "", [0, 1])


def build_comp(name, variables, constraints, **params):
    graph = chg.build_computation_graph(
        variables=variables, constraints=constraints)
    algo = AlgorithmDef.build_with_default_param(
        "mgm2", params, "min")
    defs = {n.name: ComputationDef(n, algo) for n in graph.nodes}
    comp = Mgm2Computation(defs[name])
    comp._msg_sender = MagicMock()
    return comp


def two_var(matrix, **params):
    """v1 -- v2 with the given 2x2 cost matrix; returns v1's comp."""
    v1, v2 = Variable("v1", d2), Variable("v2", d2)
    c = NAryMatrixRelation([v1, v2], np.array(matrix), "c1")
    return build_comp("v1", [v1, v2], [c], **params)


def sent(comp, msg_type=None):
    """(target, message) pairs sent so far, optionally filtered."""
    out = []
    for call in comp._msg_sender.call_args_list:
        target, msg = call[0][1], call[0][2]
        if msg_type is None or msg.type == msg_type:
            out.append((target, msg))
    return out


def start_at(comp, value):
    """Start the computation and pin its current value."""
    random.seed(0)
    comp.start()
    comp.value_selection(value, None)
    comp._msg_sender.reset_mock()


class TestStartAndRoles:
    def test_start_broadcasts_value(self):
        comp = two_var([[0, 1], [1, 0]])
        random.seed(0)
        comp.start()
        msgs = sent(comp, "mgm2_value")
        assert [t for t, _ in msgs] == ["v2"]

    def test_no_neighbor_variable_finishes_immediately(self):
        v1 = Variable("v1", d2)
        v9 = Variable("v9", d2)
        c = NAryMatrixRelation([v9], np.array([0, 1]), "u9")
        comp = build_comp("v1", [v1, v9], [c])
        comp.start()
        assert not comp.is_running

    def test_threshold_one_always_offerer(self):
        comp = two_var([[0, 1], [1, 0]], threshold=1.0)
        start_at(comp, 0)
        comp.on_message("v2", Mgm2ValueMessage(0), 0)
        assert comp._is_offerer
        assert comp._partner == "v2"

    def test_threshold_zero_never_offerer(self):
        comp = two_var([[0, 1], [1, 0]], threshold=0.0)
        start_at(comp, 0)
        comp.on_message("v2", Mgm2ValueMessage(0), 0)
        assert not comp._is_offerer


class TestOffers:
    def test_offerer_enumerates_joint_moves_with_gains(self):
        # cost(v1,v2): current (0,0)=4; best joint (1,1)=0
        comp = two_var([[4, 9], [9, 0]], threshold=1.0)
        start_at(comp, 0)
        comp.on_message("v2", Mgm2ValueMessage(0), 0)
        offers = dict(sent(comp, "mgm2_offer"))["v2"].offers
        assert len(offers) == 4    # 2x2 joint assignments
        gains = {(mv, pv): g for mv, pv, g in offers}
        assert gains[(1, 1)] == 4  # 4 -> 0
        assert gains[(0, 0)] == 0
        assert gains[(1, 0)] == -5

    def test_non_offerer_sends_empty_offers(self):
        comp = two_var([[0, 1], [1, 0]], threshold=0.0)
        start_at(comp, 0)
        comp.on_message("v2", Mgm2ValueMessage(0), 0)
        offers = dict(sent(comp, "mgm2_offer"))["v2"].offers
        assert offers == []

    def test_non_offerer_accepts_best_positive_offer(self):
        comp = two_var([[4, 9], [9, 0]], threshold=0.0)
        start_at(comp, 0)
        comp.on_message("v2", Mgm2ValueMessage(0), 0)
        # v2 offers (their_v, my_v, offerer_gain); my side adds gain
        # over my non-shared constraints (none here).
        comp.on_message(
            "v2", Mgm2OfferMessage([(1, 1, 4.0), (0, 1, -5.0)]), 0)
        resp = dict(sent(comp, "mgm2_response"))["v2"]
        assert resp.accept is True
        assert resp.my_value == 1      # what I asked v2... offerer's v
        assert comp._coordinated
        assert comp._committed_gain == 4.0
        assert comp._new_value == 1

    def test_non_offerer_rejects_non_positive_offers(self):
        comp = two_var([[0, 1], [1, 0]], threshold=0.0)
        start_at(comp, 0)
        comp.on_message("v2", Mgm2ValueMessage(0), 0)
        comp.on_message(
            "v2", Mgm2OfferMessage([(1, 1, 0.0), (1, 0, -1.0)]), 0)
        resp = dict(sent(comp, "mgm2_response"))["v2"]
        assert resp.accept is False
        assert not comp._coordinated

    def test_offerer_rejects_incoming_offers(self):
        comp = two_var([[4, 9], [9, 0]], threshold=1.0)
        start_at(comp, 0)
        comp.on_message("v2", Mgm2ValueMessage(0), 0)
        comp._msg_sender.reset_mock()
        comp.on_message("v2", Mgm2OfferMessage([(1, 1, 9.0)]), 0)
        resp = dict(sent(comp, "mgm2_response"))["v2"]
        assert resp.accept is False


class TestGainAndGo:
    def _coordinated_comp(self):
        comp = two_var([[4, 9], [9, 0]], threshold=0.0)
        start_at(comp, 0)
        comp.on_message("v2", Mgm2ValueMessage(0), 0)
        comp.on_message("v2", Mgm2OfferMessage([(1, 1, 4.0)]), 0)
        assert comp._coordinated
        comp._msg_sender.reset_mock()
        return comp

    def test_coordinated_pair_gain_excluded_from_contest(self):
        comp = self._coordinated_comp()
        # The partner's own gain broadcast must not veto the pair.
        comp.on_message("v2", Mgm2GainMessage(4.0), 0)
        gos = sent(comp, "mgm2_go")
        assert gos and gos[0][1].go is True

    def test_coordinated_move_on_both_go(self):
        comp = self._coordinated_comp()
        comp.on_message("v2", Mgm2GainMessage(4.0), 0)
        comp.on_message("v2", Mgm2GoMessage(True), 0)
        assert comp.current_value == 1   # moved

    def test_coordinated_no_move_on_partner_no_go(self):
        comp = self._coordinated_comp()
        comp.on_message("v2", Mgm2GainMessage(4.0), 0)
        comp.on_message("v2", Mgm2GoMessage(False), 0)
        assert comp.current_value == 0   # stayed

    def test_unilateral_strict_winner_moves(self):
        # 3-var chain: v1-v2, v2-v3; drive v2.
        v1, v2, v3 = (Variable(n, d2) for n in ("v1", "v2", "v3"))
        c1 = NAryMatrixRelation([v1, v2], np.array([[3, 0], [0, 3]]),
                                "c1")
        c2 = NAryMatrixRelation([v2, v3], np.array([[3, 0], [0, 3]]),
                                "c2")
        comp = build_comp("v2", [v1, v2, v3], [c1, c2], threshold=0.0)
        start_at(comp, 0)
        comp.on_message("v1", Mgm2ValueMessage(0), 0)
        comp.on_message("v3", Mgm2ValueMessage(0), 0)
        # both neighbors sent no real offers
        comp.on_message("v1", Mgm2OfferMessage([]), 0)
        comp.on_message("v3", Mgm2OfferMessage([]), 0)
        # my unilateral gain: cost(0)=6 -> cost(1)=0, gain 6
        gains = sent(comp, "mgm2_gain")
        assert {t for t, _ in gains} == {"v1", "v3"}
        assert gains[0][1].gain == 6.0
        comp.on_message("v1", Mgm2GainMessage(2.0), 0)
        comp.on_message("v3", Mgm2GainMessage(5.0), 0)
        assert comp.current_value == 1   # strict winner moved

    def test_unilateral_loser_stays(self):
        v1, v2, v3 = (Variable(n, d2) for n in ("v1", "v2", "v3"))
        c1 = NAryMatrixRelation([v1, v2], np.array([[1, 0], [0, 1]]),
                                "c1")
        c2 = NAryMatrixRelation([v2, v3], np.array([[1, 0], [0, 1]]),
                                "c2")
        comp = build_comp("v2", [v1, v2, v3], [c1, c2], threshold=0.0)
        start_at(comp, 0)
        for n in ("v1", "v3"):
            comp.on_message(n, Mgm2ValueMessage(0), 0)
        for n in ("v1", "v3"):
            comp.on_message(n, Mgm2OfferMessage([]), 0)
        comp.on_message("v1", Mgm2GainMessage(99.0), 0)
        comp.on_message("v3", Mgm2GainMessage(0.0), 0)
        assert comp.current_value == 0   # neighbor won


class TestRobustness:
    def test_stale_response_from_non_partner_ignored(self):
        comp = two_var([[0, 1], [1, 0]], threshold=1.0)
        start_at(comp, 0)
        comp.on_message("v2", Mgm2ValueMessage(0), 0)
        before = comp._coordinated
        comp.on_message(
            "v9", Mgm2ResponseMessage(True, 1, 1, 9.0), 0)
        assert comp._coordinated == before

    def test_early_offer_postponed_until_offer_phase(self):
        comp = two_var([[4, 9], [9, 0]], threshold=0.0)
        start_at(comp, 0)
        # Offer arrives BEFORE the value phase completes.
        comp.on_message("v2", Mgm2OfferMessage([(1, 1, 4.0)]), 0)
        assert comp._phase == "value"
        comp.on_message("v2", Mgm2ValueMessage(0), 0)
        # Entering the offer phase replays the postponed offer.
        resp = dict(sent(comp, "mgm2_response"))["v2"]
        assert resp.accept is True

    def test_new_round_rebroadcasts_value(self):
        comp = two_var([[0, 1], [1, 0]], threshold=0.0)
        start_at(comp, 0)
        comp.on_message("v2", Mgm2ValueMessage(1), 0)
        comp.on_message("v2", Mgm2OfferMessage([]), 0)
        comp._msg_sender.reset_mock()
        comp.on_message("v2", Mgm2GainMessage(0.0), 0)
        # Round ended: a fresh value broadcast starts the next one.
        values = sent(comp, "mgm2_value")
        assert [t for t, _ in values] == ["v2"]
        assert comp._phase == "value"

    def test_stop_cycle_finishes(self):
        comp = two_var([[0, 1], [1, 0]], threshold=0.0,
                       stop_cycle=1)
        start_at(comp, 0)
        comp.on_message("v2", Mgm2ValueMessage(1), 0)
        comp.on_message("v2", Mgm2OfferMessage([]), 0)
        comp.on_message("v2", Mgm2GainMessage(0.0), 0)
        assert not comp.is_running

"""Resilience subsystem: fault injection, checkpoint/resume, retry.

The reference ships resilience as a first-class capability
(ResilientAgent, computation replication, distribution reparation);
this package adds the pieces that *exercise* and *harden* that stack:

- :mod:`pydcop_tpu.resilience.faults` — deterministic, seed-driven
  fault injection (message drop / duplicate / delay / partition, agent
  crash schedules) over any ``CommunicationLayer``;
- :mod:`pydcop_tpu.resilience.checkpoint` — NPZ snapshots of
  device-resident solver state plus ``resume_from_checkpoint`` so an
  interrupted (or preempted multi-host) solve restarts mid-run;
- :mod:`pydcop_tpu.resilience.retry` — ``RetryPolicy`` (exponential
  backoff + jitter + deadline) and ``CircuitBreaker``, applied to the
  HTTP transport, remote messaging and the multihost coordinator join.

See docs/resilience.md for knobs and the agent-repair flow.
"""

from pydcop_tpu.resilience.retry import (  # noqa: F401
    CircuitBreaker,
    CircuitOpenError,
    RetryExhaustedError,
    RetryPolicy,
)

"""Constraint functions loaded from an external source file by
``coloring_chain_func.yaml`` (the local analogue of the reference's
external-python-constraint feature, reference
tests/instances/graph_coloring1_func.yaml)."""


def clash(x, y):
    """Penalty-3 difference constraint between two hue variables."""
    return 3 if x == y else 0

"""``pydcop batch``: run benchmark sweeps defined in a YAML file.

Reference parity: pydcop/commands/batch.py (run_batches :149, progress
registration :501, ``--simulate``) and the format spec
docs/usage/file_formats/batch_format.yaml:

- ``sets``: named problem sets — a ``path`` glob of input files and/or
  an ``iterations`` count, plus optional ``env`` expansion variables;
- ``batches``: named commands — ``command`` (e.g. ``solve``),
  ``command_options`` (scalars, lists = cartesian sweep, dicts =
  repeated ``name:value`` options), ``global_options`` and an optional
  ``current_dir``;
- variable expansion in option strings: {set}, {batch}, {iteration},
  {file_path}, {dir_path}, {file_basename}, {file_name}, the set's
  ``env`` entries and every command-option name.

Jobs that ran without error are appended to a ``progress_<name>`` file
next to the definition file; on restart those jobs are skipped, which
makes interrupted batches resumable.  ``--simulate`` prints the
commands without running them.
"""

import itertools
import glob
import logging
import os
import subprocess
import sys
from typing import Dict, List, Tuple

import yaml

logger = logging.getLogger("pydcop.cli.batch")


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "batch", help="run benchmark batches from a yaml definition")
    parser.add_argument("bench_file", help="batches definition file")
    parser.add_argument("--simulate", action="store_true", default=False,
                        help="print the commands without running them")
    parser.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    with open(args.bench_file, encoding="utf-8") as f:
        definition = yaml.safe_load(f)
    progress_file = os.path.join(
        os.path.dirname(os.path.abspath(args.bench_file)),
        "progress_" + os.path.basename(args.bench_file),
    )
    done = _load_progress(progress_file)
    jobs = list(iter_jobs(definition))
    logger.info("%d jobs in batch (%d already done)", len(jobs),
                len(done))
    failures = 0
    for cli_args, current_dir, job_id in jobs:
        if job_id in done:
            continue
        display = "pydcop " + " ".join(cli_args)
        if args.simulate:
            print(display)
            continue
        logger.info("Running: %s", display)
        if current_dir:
            os.makedirs(current_dir, exist_ok=True)
        try:
            subprocess.run(
                [sys.executable, "-m", "pydcop_tpu.dcop_cli"]
                + cli_args,
                cwd=current_dir or None,
                check=True,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
        except subprocess.CalledProcessError as e:
            failures += 1
            logger.error("Job failed (rc %s): %s", e.returncode, display)
            continue
        _register_job(progress_file, job_id)
    if args.simulate:
        return 0
    if failures:
        print(f"batch finished with {failures} failed job(s)")
        return 1
    # Complete: mark the progress file as done (reference renames it).
    if os.path.exists(progress_file):
        os.replace(
            progress_file,
            progress_file.replace("progress_", "done_", 1),
        )
    return 0


def iter_jobs(definition: Dict):
    """Yield (cli_args, current_dir, job_id) for every job of the
    batch definition."""
    sets = definition.get("sets", {"default": {"iterations": 1}})
    batches = definition.get("batches", {})
    global_options = definition.get("global_options", {})
    for set_name, set_def in sets.items():
        set_def = set_def or {}
        iterations = int(set_def.get("iterations", 1))
        env = set_def.get("env", {}) or {}
        files: List[List[str]] = []
        if "path" in set_def:
            path = os.path.expanduser(set_def["path"])
            if os.path.isdir(path):
                path = os.path.join(path, "*")
            files = [[f] for f in sorted(glob.glob(path))]
        else:
            files = [[]]
        for file_group in files:
            for iteration in range(iterations):
                context = dict(env)
                context.update({
                    "set": set_name,
                    "iteration": iteration,
                })
                if file_group:
                    fp = file_group[0]
                    context.update({
                        "file_path": fp,
                        "dir_path": os.path.dirname(fp),
                        "file_basename": os.path.basename(fp),
                        "file_name": os.path.splitext(
                            os.path.basename(fp))[0],
                    })
                for batch_name, batch_def in batches.items():
                    yield from _batch_jobs(
                        batch_name, batch_def, context, file_group,
                        global_options,
                    )


def _batch_jobs(batch_name: str, batch_def: Dict, context: Dict,
                file_group: List[str], global_options: Dict):
    command = batch_def.get("command", "solve")
    command_options = batch_def.get("command_options", {}) or {}
    batch_globals = dict(global_options)
    batch_globals.update(batch_def.get("global_options", {}) or {})
    context = dict(context)
    context["batch"] = batch_name
    for combo in _expand_option_combinations(command_options):
        job_context = dict(context)
        for name, value in combo:
            # dicts stay dicts so "{opts[key]}" expansion works.
            job_context[name] = value
        cli_args: List[str] = []
        for name, value in sorted(batch_globals.items()):
            cli_args += ["--" + name, _expand(str(value), job_context)]
        cli_args += command.split()
        for name, value in combo:
            if isinstance(value, dict):
                for k, v in value.items():
                    cli_args += [
                        "--" + name,
                        f"{k}:{_expand(str(v), job_context)}",
                    ]
            else:
                cli_args += [
                    "--" + name, _expand(str(value), job_context)
                ]
        cli_args += file_group
        current_dir = batch_def.get("current_dir")
        if current_dir:
            current_dir = os.path.expanduser(
                _expand(current_dir, job_context))
        job_id = " ".join(cli_args) + f" #it{job_context['iteration']}"
        yield cli_args, current_dir, job_id


def _expand_option_combinations(options: Dict) -> List[List[Tuple]]:
    """Cartesian product over list-valued options (reference batch
    sweep semantics); dict values sweep over their list-valued
    entries."""
    axes = []
    for name, value in sorted(options.items()):
        if isinstance(value, list):
            axes.append([(name, v) for v in value])
        elif isinstance(value, dict):
            sub_axes = []
            for k, v in sorted(value.items()):
                if isinstance(v, list):
                    sub_axes.append([(k, sv) for sv in v])
                else:
                    sub_axes.append([(k, v)])
            axes.append([
                (name, dict(sub_combo))
                for sub_combo in itertools.product(*sub_axes)
            ])
        else:
            axes.append([(name, value)])
    return [list(combo) for combo in itertools.product(*axes)]


def _expand(template: str, context: Dict) -> str:
    try:
        return template.format(**context)
    except (KeyError, IndexError):
        return template


def _load_progress(progress_file: str) -> set:
    if not os.path.exists(progress_file):
        return set()
    with open(progress_file, encoding="utf-8") as f:
        return {line.rstrip("\n") for line in f if line.strip()}


def _register_job(progress_file: str, job_id: str):
    with open(progress_file, "a", encoding="utf-8") as f:
        f.write(job_id + "\n")

"""Shared machinery for distribution methods.

Reference parity: the common structure behind pydcop/distribution/*
modules — footprint/capacity accounting, communication edges, hosting
and route costs, plus two placement engines:

- a greedy engine (used by adhoc/heur_comhost/gh_*): place computations
  one at a time on the cheapest feasible agent;
- an ILP engine (used by ilp_*/oilp_*): binary x[c,a] placement
  variables with per-edge y[e,a1,a2] linearization of route costs,
  solved with scipy.optimize.milp (the reference uses PuLP, which is
  not available in this image; the model is the same).

Distribution cost convention (reference ilp_compref.py): total =
RATIO * comm + (1 - RATIO) * hosting, with comm = sum over edges of
route(a1,a2) * communication_load, hosting = sum of hosting_cost(a,c).
"""

from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from pydcop_tpu.distribution.objects import (
    Distribution,
    DistributionHints,
    ImpossibleDistributionException,
)

RATIO_HOST_COMM = 0.8


def footprints(cg, computation_memory: Optional[Callable]
               ) -> Dict[str, float]:
    out = {}
    for node in cg.nodes:
        if computation_memory is None:
            out[node.name] = 0.0
        else:
            try:
                out[node.name] = float(computation_memory(node))
            except (NotImplementedError, TypeError):
                out[node.name] = 0.0
    return out


def comm_edges(cg, communication_load: Optional[Callable]
               ) -> List[Tuple[str, str, float]]:
    """Unique (comp1, comp2, load) pairs for linked computations."""
    edges = {}
    for node in cg.nodes:
        for neighbor in node.neighbors:
            key = tuple(sorted((node.name, neighbor)))
            if key in edges:
                continue
            if communication_load is None:
                load = 1.0
            else:
                try:
                    load = float(communication_load(node, neighbor))
                except (NotImplementedError, TypeError, ValueError):
                    load = 1.0
            edges[key] = load
    return [(a, b, load) for (a, b), load in edges.items()]


def agent_capacity(agent) -> float:
    try:
        return float(agent.capacity)
    except (AttributeError, TypeError):
        return float("inf")


def distribution_cost_impl(distribution: Distribution, cg, agentsdef,
                           computation_memory=None,
                           communication_load=None,
                           ratio: float = RATIO_HOST_COMM
                           ) -> Tuple[float, float, float]:
    """(total, comm, hosting) costs of a distribution."""
    agents = {a.name: a for a in agentsdef}
    comm = 0.0
    for c1, c2, load in comm_edges(cg, communication_load):
        a1 = distribution.agent_for(c1)
        a2 = distribution.agent_for(c2)
        comm += agents[a1].route(a2) * load
    hosting = 0.0
    for comp in distribution.computations:
        agent = agents[distribution.agent_for(comp)]
        hosting += agent.hosting_cost(comp)
    total = ratio * comm + (1 - ratio) * hosting
    return total, comm, hosting


def greedy_place(
    cg, agentsdef: Iterable, hints: Optional[DistributionHints],
    computation_memory, communication_load,
    order_key: Optional[Callable] = None,
    comm_weight: float = 1.0,
    hosting_weight: float = 1.0,
) -> Distribution:
    """Greedy placement: hints first, then computations in `order_key`
    order, each on the cheapest feasible agent (capacity respected)."""
    agents = {a.name: a for a in agentsdef}
    if not agents:
        raise ImpossibleDistributionException("No agents")
    fp = footprints(cg, computation_memory)
    edges = comm_edges(cg, communication_load)
    neighbors_of: Dict[str, List[Tuple[str, float]]] = {}
    for a, b, load in edges:
        neighbors_of.setdefault(a, []).append((b, load))
        neighbors_of.setdefault(b, []).append((a, load))

    remaining_capacity = {
        name: agent_capacity(a) for name, a in agents.items()
    }
    placed: Dict[str, str] = {}

    def host(comp: str, agent: str):
        if fp[comp] > remaining_capacity[agent]:
            raise ImpossibleDistributionException(
                f"Agent {agent} has no capacity left for {comp} "
                f"(needs {fp[comp]}, has {remaining_capacity[agent]})"
            )
        remaining_capacity[agent] -= fp[comp]
        placed[comp] = agent

    comp_names = {n.name for n in cg.nodes}

    # 1. must_host hints.
    if hints is not None:
        for agent in agents:
            for comp in hints.must_host(agent):
                if comp in comp_names and comp not in placed:
                    host(comp, agent)

    # 2. Remaining computations, ordered.
    todo = [n.name for n in cg.nodes if n.name not in placed]
    if order_key is not None:
        todo.sort(key=lambda c: order_key(c, fp, neighbors_of))

    for comp in todo:
        best_agent, best_cost = None, None
        for name, agent in agents.items():
            if fp[comp] > remaining_capacity[name]:
                continue
            cost = hosting_weight * agent.hosting_cost(comp)
            for other, load in neighbors_of.get(comp, []):
                if other in placed:
                    cost += comm_weight * load * agent.route(
                        placed[other])
            # Prefer hint co-location.
            if hints is not None:
                group = hints.host_with(comp)
                if any(placed.get(g) == name for g in group):
                    cost -= 1000
            if best_cost is None or cost < best_cost:
                best_agent, best_cost = name, cost
        if best_agent is None:
            raise ImpossibleDistributionException(
                f"No agent has capacity for computation {comp}"
            )
        host(comp, best_agent)

    mapping: Dict[str, List[str]] = {a: [] for a in agents}
    for comp, agent in placed.items():
        mapping[agent].append(comp)
    return Distribution(mapping)


def ilp_place(
    cg, agentsdef: Iterable, hints: Optional[DistributionHints],
    computation_memory, communication_load,
    comm_weight: float = 1.0,
    hosting_weight: float = 0.0,
    timeout: Optional[float] = None,
    pinned: Optional[Dict[str, str]] = None,
    require_nonempty_agents: bool = False,
) -> Distribution:
    """Optimal placement by mixed-integer programming.

    Variables: x[c,a] in {0,1} (computation c on agent a) and, per comm
    edge e=(c1,c2) and agent pair (a1,a2), y[e,a1,a2] >= x[c1,a1] +
    x[c2,a2] - 1 (continuous in [0,1]; minimization makes it exact).

    ``pinned`` forces computation -> agent assignments (the SECP
    actuator rule); ``require_nonempty_agents`` adds the oilp_secp_*
    constraint that every agent with no pinned computation hosts at
    least one (reference oilp_secp_fgdp.py:229-236).
    """
    from scipy.optimize import LinearConstraint, milp
    from scipy.sparse import lil_matrix

    agents = list(agentsdef)
    agent_names = [a.name for a in agents]
    comps = [n.name for n in cg.nodes]
    if not agents:
        raise ImpossibleDistributionException("No agents")
    na, nc = len(agents), len(comps)
    fp = footprints(cg, computation_memory)
    edges = comm_edges(cg, communication_load) if comm_weight > 0 else []

    def xi(c: int, a: int) -> int:
        return c * na + a

    n_x = nc * na
    n_y = len(edges) * na * na
    n_vars = n_x + n_y

    def yi(e: int, a1: int, a2: int) -> int:
        return n_x + (e * na + a1) * na + a2

    objective = np.zeros(n_vars)
    if hosting_weight:
        for c, comp in enumerate(comps):
            for a, agent in enumerate(agents):
                objective[xi(c, a)] = (
                    hosting_weight * agent.hosting_cost(comp)
                )
    comp_index = {comp: i for i, comp in enumerate(comps)}
    for e, (c1, c2, load) in enumerate(edges):
        for a1 in range(na):
            for a2 in range(na):
                route = agents[a1].route(agent_names[a2])
                objective[yi(e, a1, a2)] = comm_weight * load * route

    constraints = []
    # Each computation hosted exactly once.
    m = lil_matrix((nc, n_vars))
    for c in range(nc):
        for a in range(na):
            m[c, xi(c, a)] = 1
    constraints.append(LinearConstraint(m.tocsr(), 1, 1))
    # Capacity.
    m = lil_matrix((na, n_vars))
    ub = np.zeros(na)
    for a, agent in enumerate(agents):
        for c, comp in enumerate(comps):
            m[a, xi(c, a)] = fp[comp]
        ub[a] = agent_capacity(agent)
    constraints.append(LinearConstraint(m.tocsr(), -np.inf, ub))
    # Edge linearization: y >= x1 + x2 - 1.
    if edges:
        m = lil_matrix((len(edges) * na * na, n_vars))
        row = 0
        for e, (c1, c2, _) in enumerate(edges):
            i1, i2 = comp_index[c1], comp_index[c2]
            for a1 in range(na):
                for a2 in range(na):
                    m[row, xi(i1, a1)] = 1
                    m[row, xi(i2, a2)] = 1
                    m[row, yi(e, a1, a2)] = -1
                    row += 1
        constraints.append(
            LinearConstraint(m.tocsr(), -np.inf, 1))
    # Each agent without a pinned computation hosts at least one.
    if require_nonempty_agents:
        pinned_agents = set((pinned or {}).values())
        empty = [
            a for a, agent in enumerate(agents)
            if agent.name not in pinned_agents
        ]
        if empty:
            m = lil_matrix((len(empty), n_vars))
            for row, a in enumerate(empty):
                for c in range(nc):
                    m[row, xi(c, a)] = 1
            constraints.append(
                LinearConstraint(m.tocsr(), 1, np.inf))
    # must_host hints and pinned assignments fix x variables.
    lb = np.zeros(n_vars)
    ub_v = np.ones(n_vars)
    if hints is not None:
        for a, agent in enumerate(agents):
            for comp in hints.must_host(agent.name):
                if comp in comp_index:
                    lb[xi(comp_index[comp], a)] = 1
    if pinned:
        agent_index = {name: i for i, name in enumerate(agent_names)}
        for comp, agent_name in pinned.items():
            if comp in comp_index and agent_name in agent_index:
                lb[xi(comp_index[comp], agent_index[agent_name])] = 1

    integrality = np.zeros(n_vars)
    integrality[:n_x] = 1  # x binary, y continuous

    from scipy.optimize import Bounds

    options = {"time_limit": timeout} if timeout else None
    res = milp(
        c=objective, constraints=constraints,
        integrality=integrality, bounds=Bounds(lb, ub_v),
        options=options,
    )
    if not res.success:
        raise ImpossibleDistributionException(
            f"ILP infeasible: {res.message}"
        )
    x = res.x[:n_x].reshape(nc, na)
    mapping: Dict[str, List[str]] = {a: [] for a in agent_names}
    for c, comp in enumerate(comps):
        mapping[agent_names[int(np.argmax(x[c]))]].append(comp)
    return Distribution(mapping)

"""Injectable link-fault plane for the serving fleet.

Every socket exchange the fleet makes — router→replica forwards,
liveness probes, SSE proxies, worker→router ``/fleet/join``
announcements — routes through :func:`exchange` / :func:`open_stream`
(``tools/static_check.py`` lints that nothing in ``serving/`` opens a
socket any other way).  With no plan installed the seam is a branch
and a plain ``http.client`` round trip; with one installed it injects
seeded, deterministic per-link faults:

- ``drop``: the request is never sent (connect refused) — retry-safe.
- ``delay_ms``: fixed latency added before the bytes go out.
- ``dup``: the request is delivered *twice* (second response
  discarded) — the idempotency probe.
- ``lose_response``: the request is delivered and executed but the
  response evaporates — the ambiguous failure that forces
  retry-after-bytes-sent.
- ``blackhole`` / ``partition``: the link eats traffic; calls hold
  (bounded) and fail without delivering.

Plans come from the ``PYDCOP_NETFAULT`` environment variable (reaches
spawned fleet workers) or :func:`install` (same-process test hook).
Grammar — ``;``-separated clauses of ``,``-separated ``key=value``::

    seed=7;link=router>replica-*,drop=0.01,delay_ms=20
    link=router>hostB,lose_response=1.0,times=1
    partition=host0/hostB

``link=SRC>DST`` scopes a clause to links whose endpoint labels
fnmatch the patterns (endpoints carry several labels: ``replica-3``
*and* its host id); ``path=GLOB`` further scopes it to matching
request paths (``path=/solve`` faults forwards but not the liveness
probes sharing the link).  ``times=N`` retires a clause after it has
injected N faults.  ``partition=A/B`` (groups ``+``-separated) is a
bidirectional blackhole between the two label groups.

Determinism: each probabilistic draw hashes
``seed|src|dst|attempt#|field`` — the same plan over the same call
sequence injects the same faults, regardless of thread timing
elsewhere in the fleet.
"""

from __future__ import annotations

import hashlib
import http.client
import os
import threading
import time
from dataclasses import dataclass, field
from fnmatch import fnmatch
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

__all__ = [
    "NotSent", "FaultPlan", "exchange", "open_stream",
    "install", "clear", "plan", "counters",
]

Labels = Union[str, Sequence[str]]


class NotSent(OSError):
    """The request was never delivered (zero bytes reached the peer).

    Safe to retry anywhere: raised for real connect failures and for
    injected drop/blackhole/partition faults.  ``FleetRouter``
    re-exports this as ``ForwardNotSent``.
    """


def _labels(x: Labels) -> Tuple[str, ...]:
    if isinstance(x, str):
        return (x,)
    return tuple(s for s in x if s)


def _match(pattern: str, labels: Tuple[str, ...]) -> bool:
    return any(fnmatch(lab, pattern) for lab in labels)


@dataclass
class _Clause:
    src: str = "*"
    dst: str = "*"
    path: str = "*"
    drop: float = 0.0
    delay_ms: float = 0.0
    dup: float = 0.0
    lose_response: float = 0.0
    blackhole: bool = False
    times: Optional[int] = None
    hold_s: float = 0.2          # bounded blackhole hold (tests stay fast)
    fired: int = 0

    def live(self) -> bool:
        return self.times is None or self.fired < self.times


@dataclass
class _Partition:
    group_a: List[str] = field(default_factory=list)
    group_b: List[str] = field(default_factory=list)
    hold_s: float = 0.2

    def severs(self, src: Tuple[str, ...], dst: Tuple[str, ...]) -> bool:
        a_src = any(_match(p, src) for p in self.group_a)
        b_src = any(_match(p, src) for p in self.group_b)
        a_dst = any(_match(p, dst) for p in self.group_a)
        b_dst = any(_match(p, dst) for p in self.group_b)
        return (a_src and b_dst) or (b_src and a_dst)


class FaultPlan:
    """A parsed, seeded fault plan over the fleet's links."""

    def __init__(self, clauses: Iterable[_Clause] = (),
                 partitions: Iterable[_Partition] = (),
                 seed: int = 0):
        self.clauses: List[_Clause] = list(clauses)
        self.partitions: List[_Partition] = list(partitions)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._attempts: Dict[Tuple[str, str], int] = {}
        self._injected: Dict[str, int] = {}

    # ---------------------------------------------------- parsing
    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        clauses: List[_Clause] = []
        partitions: List[_Partition] = []
        seed = 0
        for raw in spec.split(";"):
            raw = raw.strip()
            if not raw:
                continue
            pairs = {}
            for item in raw.split(","):
                if "=" not in item:
                    raise ValueError(
                        f"netfault clause item {item!r} is not key=value")
                k, v = item.split("=", 1)
                pairs[k.strip()] = v.strip()
            if "seed" in pairs:
                seed = int(pairs.pop("seed"))
            if "partition" in pairs:
                part = pairs.pop("partition")
                if "/" not in part:
                    raise ValueError(
                        "partition=A/B needs two '/'-separated groups")
                a, b = part.split("/", 1)
                partitions.append(_Partition(
                    group_a=[g for g in a.split("+") if g],
                    group_b=[g for g in b.split("+") if g],
                    hold_s=float(pairs.pop("hold_s", 0.2))))
                if pairs:
                    raise ValueError(
                        f"partition clause has stray keys {sorted(pairs)}")
                continue
            if not pairs:
                continue
            cl = _Clause()
            link = pairs.pop("link", None)
            if link is not None:
                if ">" not in link:
                    raise ValueError("link=SRC>DST needs a '>'")
                cl.src, cl.dst = (s.strip() for s in link.split(">", 1))
            for k, v in pairs.items():
                if k in ("drop", "dup", "lose_response"):
                    setattr(cl, k, float(v))
                elif k in ("delay_ms", "hold_s"):
                    setattr(cl, k, float(v))
                elif k == "blackhole":
                    cl.blackhole = v not in ("0", "false", "")
                elif k == "times":
                    cl.times = int(v)
                elif k == "path":
                    cl.path = v
                else:
                    raise ValueError(f"unknown netfault key {k!r}")
            clauses.append(cl)
        return cls(clauses, partitions, seed)

    # ------------------------------------------------- bookkeeping
    def _count(self, kind: str) -> None:
        with self._lock:
            self._injected[kind] = self._injected.get(kind, 0) + 1
        # Injections announce themselves to the trace plane: the
        # instant inherits any thread-bound trace context (the router
        # binds the forwarded request's trace_ids around the
        # exchange), so /fleet/forensics shows the fault INSIDE the
        # causal tree it perturbed.  Off the fault path this never
        # runs — the no-fault hot path stays instant-free.
        try:
            from pydcop_tpu.observability.trace import tracer

            if tracer.active:
                tracer.instant("netfault_injected", "fleet",
                               kind=kind)
        except Exception:  # noqa: BLE001 — telemetry never breaks IO
            pass

    def injected(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._injected)

    def _fraction(self, src_key: str, dst_key: str, n: int,
                  fld: str) -> float:
        h = hashlib.sha256(
            f"{self.seed}|{src_key}|{dst_key}|{n}|{fld}".encode()
        ).digest()
        return int.from_bytes(h[:8], "big") / 2.0 ** 64

    # ---------------------------------------------------- deciding
    def decide(self, src: Labels, dst: Labels, timeout: float,
               path: str = "") -> Dict[str, bool]:
        """Apply pre-send faults (may sleep / raise); return the
        post-send faults the caller must honor (``dup`` /
        ``lose_response``)."""
        src_l, dst_l = _labels(src), _labels(dst)
        src_key, dst_key = "|".join(src_l), "|".join(dst_l)
        with self._lock:
            n = self._attempts[(src_key, dst_key)] = (
                self._attempts.get((src_key, dst_key), 0) + 1)
        for part in self.partitions:
            if part.severs(src_l, dst_l):
                self._count("partition")
                time.sleep(min(timeout, part.hold_s))
                raise NotSent(
                    f"netfault: partition severs {src_key}->{dst_key}")
        post = {"dup": False, "lose_response": False}
        for cl in self.clauses:
            if not (_match(cl.src, src_l) and _match(cl.dst, dst_l)):
                continue
            if not fnmatch(path, cl.path):
                continue
            if not cl.live():
                continue
            if cl.blackhole:
                cl.fired += 1
                self._count("blackhole")
                time.sleep(min(timeout, cl.hold_s))
                raise NotSent(
                    f"netfault: black hole on {src_key}->{dst_key}")
            if cl.drop and self._fraction(
                    src_key, dst_key, n, "drop") < cl.drop:
                cl.fired += 1
                self._count("drop")
                raise NotSent(
                    f"netfault: dropped on {src_key}->{dst_key}")
            if cl.delay_ms:
                cl.fired += 1
                self._count("delay")
                time.sleep(cl.delay_ms / 1000.0)
            if cl.dup and self._fraction(
                    src_key, dst_key, n, "dup") < cl.dup:
                cl.fired += 1
                post["dup"] = True
            if cl.lose_response and self._fraction(
                    src_key, dst_key, n, "lose_response"
                    ) < cl.lose_response:
                cl.fired += 1
                post["lose_response"] = True
        return post


# ------------------------------------------------------------------
# Module-level plan registry.  ``plan()`` is the hot-path check: one
# global read once the env latch is set.
# ------------------------------------------------------------------
_PLAN: Optional[FaultPlan] = None
_ENV_LOADED = False
_LOCK = threading.Lock()


def plan() -> Optional[FaultPlan]:
    global _ENV_LOADED, _PLAN
    if not _ENV_LOADED:
        with _LOCK:
            if not _ENV_LOADED:
                spec = os.environ.get("PYDCOP_NETFAULT")
                if spec:
                    _PLAN = FaultPlan.parse(spec)
                _ENV_LOADED = True
    return _PLAN


def install(p: Union[FaultPlan, str]) -> FaultPlan:
    """Same-process test hook: activate a plan (or plan string)."""
    global _ENV_LOADED, _PLAN
    if isinstance(p, str):
        p = FaultPlan.parse(p)
    with _LOCK:
        _PLAN = p
        _ENV_LOADED = True
    return p


def clear() -> None:
    """Deactivate fault injection (also suppresses the env plan)."""
    global _ENV_LOADED, _PLAN
    with _LOCK:
        _PLAN = None
        _ENV_LOADED = True


def counters() -> Dict[str, int]:
    p = plan()
    return p.injected() if p is not None else {}


# ------------------------------------------------------------------
# The seam itself.
# ------------------------------------------------------------------
def _send(host: str, port: int, method: str, path: str,
          body: Optional[bytes], timeout: float,
          headers: Optional[Dict[str, str]]
          ) -> Tuple[int, str, bytes]:
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        try:
            conn.connect()
        except OSError as exc:
            # Zero bytes reached the peer: retry-safe by construction.
            raise NotSent(str(exc)) from exc
        hdrs = dict(headers or {})
        if body is not None and "Content-Type" not in hdrs:
            hdrs["Content-Type"] = "application/json"
        conn.request(method, path, body=body, headers=hdrs)
        resp = conn.getresponse()
        payload = resp.read()
        return (resp.status,
                resp.getheader("Content-Type", "application/json"),
                payload)
    finally:
        conn.close()


def exchange(src: Labels, dst: Labels, host: str, port: int,
             method: str, path: str, body: Optional[bytes] = None,
             timeout: float = 30.0,
             headers: Optional[Dict[str, str]] = None
             ) -> Tuple[int, str, bytes]:
    """One HTTP round trip over a named fleet link.

    Raises :class:`NotSent` when nothing was delivered (connect
    failure or injected drop/blackhole/partition) and plain
    ``OSError`` for ambiguous failures (bytes sent, outcome unknown —
    including injected ``lose_response``).
    """
    p = plan()
    if p is None:
        return _send(host, port, method, path, body, timeout, headers)
    post = p.decide(src, dst, timeout, path=path)
    out = _send(host, port, method, path, body, timeout, headers)
    if post["dup"]:
        p._count("dup")
        try:
            _send(host, port, method, path, body, timeout, headers)
        except OSError:
            pass
    if post["lose_response"]:
        p._count("lose_response")
        raise OSError(
            "netfault: response lost after delivery "
            f"({method} {path})")
    return out


def open_stream(src: Labels, dst: Labels, host: str, port: int,
                method: str, path: str, body: Optional[bytes],
                timeout: float,
                headers: Optional[Dict[str, str]] = None):
    """Open a streaming exchange (SSE proxy); returns ``(conn,
    resp)`` — the caller reads and must ``conn.close()``.

    Pre-send faults (drop/delay/blackhole/partition) apply; the
    post-send kinds don't meaningfully compose with a stream and are
    ignored.
    """
    p = plan()
    if p is not None:
        p.decide(src, dst, timeout, path=path)
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        try:
            conn.connect()
        except OSError as exc:
            raise NotSent(str(exc)) from exc
        hdrs = dict(headers or {})
        if body is not None and "Content-Type" not in hdrs:
            hdrs["Content-Type"] = "application/json"
        conn.request(method, path, body=body, headers=hdrs)
        resp = conn.getresponse()
        return conn, resp
    except Exception:
        conn.close()
        raise

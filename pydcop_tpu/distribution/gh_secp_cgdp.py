"""gh_secp_cgdp: SECP-specialized greedy heuristic, constraint graph.

Reference parity: pydcop/distribution/gh_secp_cgdp.py:75-124.  Two-step
policy for SECPs modeled as constraint graphs (only actuator and
physical-model variables exist as computations):

1. pin every actuator variable (hosting cost 0) on its agent;
2. place each remaining (model) variable on the agent that hosts the
   most of its neighbors and still has capacity, ties broken on
   remaining capacity (find_candidates, reference :142-166).

Communication load is not used; the footprint is required.
"""

from pydcop_tpu.distribution import oilp_secp_cgdp
from pydcop_tpu.distribution.objects import (
    Distribution,
    ImpossibleDistributionException,
)
from pydcop_tpu.distribution.secp_rules import (
    pin_actuators,
    place_by_affinity,
)


def distribute(computation_graph, agentsdef, hints=None,
               computation_memory=None, communication_load=None, **_):
    if computation_memory is None:
        raise ImpossibleDistributionException(
            "gh_secp_cgdp requires a computation_memory function")
    agentsdef = list(agentsdef)
    mapping, capa, remaining, _unused = pin_actuators(
        computation_graph, agentsdef, computation_memory)
    place_by_affinity(
        computation_graph, computation_memory, mapping, capa,
        [(comp,) for comp in remaining],
    )
    return Distribution({a: list(cs) for a, cs in mapping.items()})


def distribution_cost(distribution, computation_graph, agentsdef,
                      computation_memory=None, communication_load=None):
    return oilp_secp_cgdp.distribution_cost(
        distribution, computation_graph, agentsdef,
        computation_memory, communication_load)

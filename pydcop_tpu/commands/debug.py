"""``pydcop debug``: operational forensics commands.

``pydcop debug bundle`` cuts a postmortem bundle on demand — the same
document the always-on flight recorder (observability/flight.py)
dumps automatically on anomaly triggers: the trace-event ring tail,
a metrics-registry snapshot, the ``/healthz`` payload, env +
accelerator-probe diagnostics, the device-efficiency rollup
(backend-honest attainment + the where-the-time-went ledger — what
backend was the anomalous run actually executing on, and was it doing
useful work), the ``BENCH_TPU_PROBELOG.jsonl`` history tail, and the
pending-journal summary when a serve journal is active.

Two modes:

- ``pydcop debug bundle --url http://HOST:PORT`` asks a RUNNING
  process (a ``pydcop serve`` front end or any ``--serve_metrics``
  solve) for its bundle over ``GET /debug/bundle`` and saves the
  JSON locally — the mode an operator actually uses, since the
  interesting ring lives in the serving process, not in this CLI
  process;
- without ``--url``, the bundle is cut from THIS process's recorder
  (mostly a plumbing self-test: the ring holds only this command's
  own startup events).

``--out PATH`` names the output file (default: the recorder's bundle
directory / the server's reported path, printed either way).
"""

import json
import sys

import logging

logger = logging.getLogger("pydcop.cli.debug")


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "debug", help="operational forensics (postmortem bundles)")
    debug_sub = parser.add_subparsers(
        title="debug commands", dest="debug_command")

    bundle = debug_sub.add_parser(
        "bundle", help="cut a postmortem bundle on demand")
    bundle.add_argument(
        "--url", default=None, metavar="URL",
        help="telemetry endpoint of a running process "
             "(e.g. http://127.0.0.1:8080): fetches GET /debug/bundle "
             "from IT instead of bundling this CLI process")
    bundle.add_argument(
        "--out", default=None, metavar="PATH",
        help="write the bundle JSON here (default: the recorder's "
             "bundle dir, path printed)")
    bundle.add_argument(
        "--timeout", type=float, default=10.0,
        help="HTTP timeout for --url (seconds, default 10)")
    bundle.set_defaults(func=run_bundle)

    parser.set_defaults(func=_no_subcommand(parser))


def _no_subcommand(parser):
    def run(_args) -> int:
        parser.print_help(sys.stderr)
        return 2

    return run


def _fetch_remote(url: str, timeout: float):
    from urllib.request import urlopen

    endpoint = url.rstrip("/") + "/debug/bundle"
    with urlopen(endpoint, timeout=timeout) as resp:  # noqa: S310
        return json.loads(resp.read())


def run_bundle(args) -> int:
    if args.url:
        try:
            doc = _fetch_remote(args.url, args.timeout)
        except Exception as exc:  # noqa: BLE001 — CLI surface
            print(f"pydcop debug: could not fetch a bundle from "
                  f"{args.url}: {exc}", file=sys.stderr)
            return 2
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                json.dump(doc, f)
            path = args.out
        else:
            path = doc.get("path", "(remote only)")
    else:
        from pydcop_tpu.observability.flight import get_flight

        recorder = get_flight()
        if recorder is None:
            print("pydcop debug: flight recorder disabled "
                  "(PYDCOP_FLIGHT_RECORDER=0)", file=sys.stderr)
            return 2
        doc = recorder.make_bundle("on_demand", {"via": "cli"})
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                json.dump(doc, f, default=str)
            path = args.out
        else:
            path = recorder.write_bundle(doc)
    print(f"postmortem bundle ({doc.get('kind', '?')}, "
          f"{len(doc.get('events', []))} ring event(s), "
          f"pid {doc.get('pid', '?')}): {path}")
    return 0

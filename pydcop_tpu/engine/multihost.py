"""Multi-host (DCN) initialization for the device engine.

Single-host scaling rides ICI through the one-axis mesh in
engine/sharding.py.  Scaling past one host uses JAX's distributed
runtime: every host calls :func:`initialize_multihost` before any jax
call, after which ``jax.devices()`` returns the GLOBAL device list and
the same ``make_mesh()`` / ``shard_graph()`` code paths shard buckets
across hosts — XLA routes the per-superstep all-reduce over ICI within
a slice and DCN across slices.  No engine code changes: the mesh is
just bigger.

This replaces the reference's multi-machine story (one agent process
per machine + JSON-over-HTTP, pydcop/commands/agent.py +
orchestrator.py) for the *data plane*; the HTTP stack remains for
agent-mode deployments and control-plane traffic.

Environment conventions (standard jax.distributed):
- ``PYDCOP_COORDINATOR`` — "host:port" of process 0,
- ``PYDCOP_NUM_PROCESSES`` / ``PYDCOP_PROCESS_ID`` — world size / rank,
- ``PYDCOP_MULTIHOST=auto`` — call ``jax.distributed.initialize()``
  with no arguments, letting it auto-detect the topology (TPU pods).
With none of these set the initializer is a silent single-host no-op,
so the same entry points work everywhere.
"""

import logging
import os
from typing import Optional

logger = logging.getLogger("pydcop.multihost")

_initialized = False


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> bool:
    """Join the JAX distributed runtime (idempotent).

    Arguments default to the ``PYDCOP_*`` environment variables; set
    ``PYDCOP_MULTIHOST=auto`` on TPU pod slices to use
    jax.distributed's no-argument topology auto-detection.  Returns
    True when running distributed (more than one process), False for
    plain single-host runs (nothing configured — a silent no-op).
    """
    global _initialized
    if _initialized:
        import jax

        return jax.process_count() > 1

    coordinator_address = (
        coordinator_address or os.environ.get("PYDCOP_COORDINATOR")
    )
    if num_processes is None and "PYDCOP_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["PYDCOP_NUM_PROCESSES"])
    if process_id is None and "PYDCOP_PROCESS_ID" in os.environ:
        process_id = int(os.environ["PYDCOP_PROCESS_ID"])

    import jax

    if coordinator_address is None and num_processes is None:
        if os.environ.get("PYDCOP_MULTIHOST") == "auto":
            # TPU pod: no-arg initialize auto-detects the topology.
            jax.distributed.initialize()
            _initialized = True
            return jax.process_count() > 1
        # Single-host: nothing to join.
        _initialized = True
        return False

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    logger.info(
        "Joined distributed runtime: process %d/%d, %d global devices",
        jax.process_index(), jax.process_count(),
        len(jax.devices()),
    )
    return jax.process_count() > 1


def global_mesh(n_devices: Optional[int] = None):
    """A mesh over the global (cross-host) device list; call
    :func:`initialize_multihost` first on every host."""
    from pydcop_tpu.engine.sharding import make_mesh

    return make_mesh(n_devices)

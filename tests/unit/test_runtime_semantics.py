"""Runtime-semantics tests: orchestrator timeout, cost-trace runs, and
the clean-environment helper every entry point relies on."""

import time

import numpy as np

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.computations_graph import constraints_hypergraph as chg
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.distribution.objects import Distribution
from pydcop_tpu.infrastructure.run import run_local_thread_dcop
from pydcop_tpu.utils.cleanenv import scrubbed_cpu_env


def _dcop():
    d = Domain("c", "", ["R", "G", "B"])
    dcop = DCOP("t", objective="min")
    vs = [Variable(f"v{i}", d) for i in range(3)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(2):
        dcop.add_constraint(constraint_from_str(
            f"c{i}", f"1 if v{i} == v{i + 1} else 0",
            [vs[i], vs[i + 1]]))
    dcop.add_agents([AgentDef(f"a{i}") for i in range(3)])
    return dcop


class TestOrchestratorTimeout:
    def test_timeout_stops_run_and_sets_status(self):
        """A non-terminating algorithm (maxsum has no stop condition)
        must be cut at the timeout with status TIMEOUT, and the
        orchestrator must still produce final metrics (reference
        orchestrator.py:270-276 timeout timer)."""
        dcop = _dcop()
        algo = AlgorithmDef.build_with_default_param(
            "maxsum", mode="min")
        from pydcop_tpu.computations_graph import factor_graph as fg

        cg = fg.build_computation_graph(dcop)
        mapping = {"a0": [], "a1": [], "a2": []}
        for i, node in enumerate(cg.nodes):
            mapping[f"a{i % 3}"].append(node.name)
        orch = run_local_thread_dcop(
            algo, cg, Distribution(mapping), dcop)
        try:
            assert orch.wait_ready(10)
            orch.deploy_computations()
            t0 = time.perf_counter()
            orch.run(timeout=1.5)
            elapsed = time.perf_counter() - t0
            assert orch.status == "TIMEOUT"
            # The run returned promptly after the timeout, not after
            # some much longer internal grace period.
            assert elapsed < 10
            orch.stop_agents(5)
            metrics = orch.end_metrics()
            assert set(metrics["assignment"]) >= {"v0", "v1", "v2"}
        finally:
            orch.stop_agents(2)
            orch.stop()

    def test_finished_status_when_algorithm_terminates(self):
        """A terminating algorithm (dsa with stop_cycle) ends the run
        with FINISHED before the timeout."""
        dcop = _dcop()
        algo = AlgorithmDef(
            "dsa", {"stop_cycle": 10, "variant": "B",
                    "probability": 0.7}, "min")
        cg = chg.build_computation_graph(dcop)
        mapping = {"a0": [], "a1": [], "a2": []}
        for i, node in enumerate(cg.nodes):
            mapping[f"a{i % 3}"].append(node.name)
        orch = run_local_thread_dcop(
            algo, cg, Distribution(mapping), dcop)
        try:
            assert orch.wait_ready(10)
            orch.deploy_computations()
            orch.run(timeout=20)
            assert orch.status == "FINISHED"
        finally:
            orch.stop_agents(5)
            orch.stop()


class TestCostTrace:
    def test_trace_monotone_overall_and_matches_final(self):
        from pydcop_tpu.engine.compile import compile_dcop
        from pydcop_tpu.engine.runner import MaxSumEngine

        dcop = _dcop()
        graph, meta = compile_dcop(dcop, noise_level=0.01)
        engine = MaxSumEngine(graph, meta)
        res = engine.run_trace(max_cycles=40)
        trace = res.metrics["cost_trace"]
        assert trace.shape == (40,)
        # The final trace entry equals the host-evaluated cost of the
        # returned assignment (device cost accounting is consistent).
        host_cost, _ = dcop.solution_cost(res.assignment)
        assert float(trace[-1]) == host_cost
        # The trajectory improved from the first cycle's cost.
        assert float(trace[-1]) <= float(trace[0])


class TestScrubbedCpuEnv:
    def test_scrub_drops_axon_and_forces_cpu(self):
        base = {
            "PALLAS_AXON_POOL_IPS": "10.0.0.1",
            "JAX_PLATFORMS": "axon",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "PATH": "/usr/bin",
        }
        env = scrubbed_cpu_env(n_devices=8, base=base)
        assert "PALLAS_AXON_POOL_IPS" not in env
        assert env["JAX_PLATFORMS"] == "cpu"
        assert env["XLA_FLAGS"].count(
            "--xla_force_host_platform_device_count=8") == 1
        assert "device_count=2" not in env["XLA_FLAGS"]
        assert env["PATH"] == "/usr/bin"

    def test_no_devices_keeps_existing_flags(self):
        base = {"XLA_FLAGS": "--foo=1"}
        env = scrubbed_cpu_env(base=base)
        assert env["XLA_FLAGS"] == "--foo=1"
        assert env["JAX_PLATFORMS"] == "cpu"

class TestMultihost:
    def test_single_host_noop(self, monkeypatch):
        """Without a coordinator the initializer is a silent no-op and
        the global mesh equals the local one."""
        import pydcop_tpu.engine.multihost as mh

        monkeypatch.setattr(mh, "_initialized", False)
        monkeypatch.delenv("PYDCOP_COORDINATOR", raising=False)
        monkeypatch.delenv("PYDCOP_NUM_PROCESSES", raising=False)
        assert mh.initialize_multihost() is False
        mesh = mh.global_mesh(4)
        assert mesh.size == 4

    def test_idempotent(self, monkeypatch):
        import pydcop_tpu.engine.multihost as mh

        monkeypatch.setattr(mh, "_initialized", False)
        monkeypatch.delenv("PYDCOP_COORDINATOR", raising=False)
        mh.initialize_multihost()
        # Second call must not try to re-join (jax.distributed raises
        # on double init); single-host path reports process_count()==1.
        assert mh.initialize_multihost() is False

    def test_env_var_plumbing(self, monkeypatch):
        """Env vars reach jax.distributed.initialize verbatim."""
        import pydcop_tpu.engine.multihost as mh

        monkeypatch.setattr(mh, "_initialized", False)
        monkeypatch.setenv("PYDCOP_COORDINATOR", "10.0.0.1:1234")
        monkeypatch.setenv("PYDCOP_NUM_PROCESSES", "2")
        monkeypatch.setenv("PYDCOP_PROCESS_ID", "1")
        calls = {}

        import jax

        def fake_init(coordinator_address=None, num_processes=None,
                      process_id=None):
            calls.update(
                addr=coordinator_address, n=num_processes,
                pid=process_id,
            )

        monkeypatch.setattr(jax.distributed, "initialize", fake_init)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        assert mh.initialize_multihost() is True
        assert calls == {"addr": "10.0.0.1:1234", "n": 2, "pid": 1}

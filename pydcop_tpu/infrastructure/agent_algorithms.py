"""Agent-mode algorithm computations (thread/process/multi-machine).

These implement the same message semantics as the device kernels, but as
per-computation message handlers running on agent threads — the
reference's execution model (and its testing trick: drive computations
directly with a mocked message sender).

Reference parity:
- maxsum: pydcop/algorithms/maxsum.py:279-721 (BSP via the synchronous
  mixin; factor update :382, variable update :623, damping :679,
  SAME_COUNT send suppression :106/:366-377);
- dsa: pydcop/algorithms/dsa.py:214-431 (async with per-cycle value
  bookkeeping);
- mgm: pydcop/algorithms/mgm.py:213-609 (value/gain two-phase rounds
  with postponed-message queues).
"""

import random
from typing import Any, Dict, List, Optional, Tuple

from pydcop_tpu.dcop.objects import VariableNoisyCostFunc
from pydcop_tpu.dcop.relations import (
    assignment_cost,
    find_optimal,
    find_optimum,
    optimal_cost_value,
)
from pydcop_tpu.infrastructure.agent_common import HypergraphComputation
from pydcop_tpu.infrastructure.computations import (
    DcopComputation,
    Message,
    MessagePassingComputation,
    SynchronousComputationMixin,
    VariableComputation,
    message_type,
    register,
)

SAME_COUNT = 4


# --------------------------------------------------------------------- #
# Shared MaxSum math (dict form — the device form lives in ops/maxsum.py)


def factor_costs_for_var(factor, variable, recv_costs: Dict, mode: str
                         ) -> Dict:
    """Marginal costs a factor sends to one of its variables: min (or
    max) over the other variables' assignments of factor cost + their
    received costs (reference maxsum.py:382)."""
    from pydcop_tpu.dcop.relations import generate_assignment_as_dict

    other_vars = [v for v in factor.dimensions if v != variable]
    costs = {}
    better = (lambda a, b: a < b) if mode == "min" else (lambda a, b: a > b)
    for d in variable.domain:
        best = None
        for asst in generate_assignment_as_dict(other_vars):
            f_val = factor(**asst, **{variable.name: d})
            sum_cost = 0
            for other, val in asst.items():
                if other in recv_costs and val in recv_costs[other]:
                    sum_cost += recv_costs[other][val]
            current = f_val + sum_cost
            if best is None or better(current, best):
                best = current
        costs[d] = best
    return costs


def costs_for_factor(variable, factor_name: str, factors: List,
                     costs: Dict) -> Dict:
    """Message a variable sends to one factor: own costs + sum of other
    factors' costs, mean-normalized (reference maxsum.py:623-674)."""
    msg_costs = {d: variable.cost_for_val(d) for d in variable.domain}
    sum_cost = 0
    for d in variable.domain:
        for f in factors:
            if f == factor_name or f not in costs:
                continue
            if d not in costs[f]:
                continue
            c = costs[f][d]
            sum_cost += c
            msg_costs[d] += c
    avg = sum_cost / len(msg_costs)
    return {d: c - avg for d, c in msg_costs.items()}


def apply_damping(costs: Dict, prev_costs: Optional[Dict],
                  damping: float) -> Dict:
    if prev_costs is None:
        return costs
    return {
        d: damping * prev_costs[d] + (1 - damping) * c
        for d, c in costs.items()
    }


def approx_match(costs: Dict, prev_costs: Optional[Dict],
                 stability: float) -> bool:
    if prev_costs is None:
        return False
    for d, c in costs.items():
        prev = prev_costs[d]
        if prev != c:
            delta = abs(prev - c)
            if prev + c == 0 or not (2 * delta / abs(prev + c)) < stability:
                return False
    return True


def select_value(variable, costs: Dict[str, Dict], mode: str
                 ) -> Tuple[Any, float]:
    """Pick the domain value minimizing own + received costs; first
    optimum in domain order wins ties (reference maxsum.py:584)."""
    best_d, best_c = None, None
    better = (lambda a, b: a < b) if mode == "min" else (lambda a, b: a > b)
    for d in variable.domain:
        c = variable.cost_for_val(d)
        for f_costs in costs.values():
            if d in f_costs:
                c += f_costs[d]
        if best_c is None or better(c, best_c):
            best_d, best_c = d, c
    return best_d, best_c


def _wrap_noisy(variable, params):
    """Wrap a plain variable in VariableNoisyCostFunc per the `noise`
    param (reference maxsum.py:477-487)."""
    noise = params.get("noise", 0.01)
    if noise and not isinstance(variable, VariableNoisyCostFunc):
        cost_func = (
            variable.cost_func
            if hasattr(variable, "cost_func")
            else (lambda _: 0)
        )
        variable = VariableNoisyCostFunc(
            variable.name, variable.domain, cost_func,
            initial_value=variable.initial_value, noise_level=noise,
        )
    return variable


def _reject_externals(factor, comp_name: str):
    """Plain MaxSum computations would silently marginalize over
    external (read-only) variables instead of fixing their value."""
    ext = [
        v.name for v in factor.dimensions
        if isinstance(v, _external_variable_type())
    ]
    if ext:
        raise ValueError(
            f"Factor {comp_name} depends on external variable(s) "
            f"{ext}: use algorithm 'maxsum_dynamic' for problems "
            "with external (read-only) variables"
        )


def send_damped(comp, prev_map: Dict, target: str, costs: Dict,
                damp: bool, damping: float, stability: float):
    """Shared damping + approx_match + SAME_COUNT send-suppression
    (reference maxsum.py:366-377,:679).  ``prev_map`` keeps the last
    SENT message per target so sender and receiver views stay
    consistent; suppressed values are never recorded."""
    prev, count = prev_map.get(target, (None, 0))
    if damp:
        costs = apply_damping(costs, prev, damping)
    if not approx_match(costs, prev, stability):
        comp.post_msg(target, MaxSumMessage(costs))
        prev_map[target] = (costs, 1)
    elif count < SAME_COUNT:
        comp.post_msg(target, MaxSumMessage(costs))
        prev_map[target] = (costs, count + 1)


class MaxSumMessage(Message):
    def __init__(self, costs: Dict):
        super().__init__("max_sum", None)
        self._costs = costs

    @property
    def costs(self) -> Dict:
        return dict(self._costs)

    @property
    def size(self) -> int:
        return 2 * len(self._costs)

    def __eq__(self, other):
        return (
            isinstance(other, MaxSumMessage) and self._costs == other._costs
        )

    def _simple_repr(self):
        vals, costs = (
            zip(*self._costs.items()) if self._costs else ((), ())
        )
        return {
            "__module__": self.__class__.__module__,
            "__qualname__": self.__class__.__qualname__,
            "vals": list(vals),
            "costs": list(costs),
        }

    @classmethod
    def _from_repr(cls, r):
        return cls(dict(zip(r["vals"], r["costs"])))

    def __repr__(self):
        return f"MaxSumMessage({self._costs})"


class MaxSumFactorComputation(SynchronousComputationMixin,
                              DcopComputation):
    """One computation per factor (constraint) in the factor graph."""

    # Dynamic subclasses (maxsum_dynamic) slice external variables out;
    # the plain computation would silently marginalize over them instead
    # of fixing their value, so it refuses them up front.
    HANDLES_EXTERNALS = False

    def __init__(self, comp_def):
        super().__init__(comp_def.node.factor.name, comp_def)
        self.factor = comp_def.node.factor
        self.variables = self.factor.dimensions
        if not self.HANDLES_EXTERNALS:
            _reject_externals(self.factor, self.name)
        self._costs: Dict[str, Dict] = {}
        params = comp_def.algo.params
        self.damping = params.get("damping", 0.5)
        self.damping_nodes = params.get("damping_nodes", "both")
        self.stability = params.get("stability", 0.1)
        self._prev: Dict[str, Tuple[Optional[Dict], int]] = {}

    @register("max_sum")
    def _on_maxsum_msg(self, sender, msg, t):
        pass  # collected by the synchronous mixin

    def footprint(self) -> float:
        return super().footprint()

    def on_new_cycle(self, messages, cycle_id):
        for sender, (msg, t) in messages.items():
            self._costs[sender] = msg.costs
        for v in self.variables:
            costs_v = factor_costs_for_var(
                self.factor, v, self._costs, self.mode
            )
            # On suppression (reference :366-377) the sync mixin emits
            # a filler instead.
            send_damped(
                self, self._prev, v.name, costs_v,
                self.damping_nodes in ("factors", "both"),
                self.damping, self.stability,
            )
        return None


class MaxSumVariableComputation(SynchronousComputationMixin,
                                VariableComputation):
    """One computation per variable in the factor graph."""

    def __init__(self, comp_def):
        params = comp_def.algo.params
        variable = _wrap_noisy(comp_def.node.variable, params)
        super().__init__(variable, comp_def)
        self.factor_names = [l.factor_node for l in comp_def.node.links]
        self._costs: Dict[str, Dict] = {}
        self.damping = params.get("damping", 0.5)
        self.damping_nodes = params.get("damping_nodes", "both")
        self.stability = params.get("stability", 0.1)
        self._prev: Dict[str, Tuple[Optional[Dict], int]] = {}

    @register("max_sum")
    def _on_maxsum_msg(self, sender, msg, t):
        pass  # collected by the synchronous mixin

    def on_start(self):
        # Select an initial value from own costs.
        value, cost = optimal_cost_value(self._variable, self.mode)
        self.value_selection(value, cost)

    def on_new_cycle(self, messages, cycle_id):
        for sender, (msg, t) in messages.items():
            self._costs[sender] = msg.costs
        value, cost = select_value(self._variable, self._costs, self.mode)
        self.value_selection(value, cost)
        for f_name in self.factor_names:
            costs_f = costs_for_factor(
                self._variable, f_name, self.factor_names, self._costs
            )
            send_damped(
                self, self._prev, f_name, costs_f,
                self.damping_nodes in ("vars", "both"),
                self.damping, self.stability,
            )
        return None


# --------------------------------------------------------------------- #
# Asynchronous MaxSum (amaxsum): per-message firing, no sync mixin
# (reference amaxsum.py:108-424; resume re-sends :165-180).


class AMaxSumFactorComputation(DcopComputation):
    """Asynchronous MaxSum factor: every incoming cost message fires an
    immediate recomputation and (suppression permitting) a send to the
    *other* variables — no cycle barrier."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.factor.name, comp_def)
        self.factor = comp_def.node.factor
        self.variables = self.factor.dimensions
        _reject_externals(self.factor, self.name)
        params = comp_def.algo.params
        self.damping = params.get("damping", 0.5)
        self.damping_nodes = params.get("damping_nodes", "both")
        self.stability = params.get("stability", 0.1)
        self._costs: Dict[str, Dict] = {}
        self._prev: Dict[str, Tuple[Optional[Dict], int]] = {}

    def on_start(self):
        self._send_to(self.variables)

    def on_pause(self, paused: bool):
        if not paused:
            # Dynamic-DCOP support: re-send current marginals on resume
            # so re-deployed neighbors re-enter the flow.
            self._prev.clear()
            self._send_to(self.variables)

    @register("max_sum")
    def _on_costs(self, sender, msg, t):
        self._costs[sender] = msg.costs
        self.new_cycle()
        # Fire to EVERY variable, sender included: with damping, each
        # (possibly identical) incoming message must re-trigger a
        # damped recomputation or messages freeze mid-trajectory —
        # SAME_COUNT re-sends keep the iteration alive until it is
        # within `stability` of the fixpoint (reference amaxsum
        # re-fires the full update per message the same way).
        self._send_to(self.variables)

    def _send_to(self, variables):
        for v in variables:
            costs_v = factor_costs_for_var(
                self.factor, v, self._costs, self.mode
            )
            send_damped(
                self, self._prev, v.name, costs_v,
                self.damping_nodes in ("factors", "both"),
                self.damping, self.stability,
            )


class AMaxSumVariableComputation(VariableComputation):
    """Asynchronous MaxSum variable: fires on every factor message,
    re-selecting its value immediately (reference amaxsum.py:251-424)."""

    def __init__(self, comp_def):
        params = comp_def.algo.params
        variable = _wrap_noisy(comp_def.node.variable, params)
        super().__init__(variable, comp_def)
        self.factor_names = [l.factor_node for l in comp_def.node.links]
        self.damping = params.get("damping", 0.5)
        self.damping_nodes = params.get("damping_nodes", "both")
        self.stability = params.get("stability", 0.1)
        self._costs: Dict[str, Dict] = {}
        self._prev: Dict[str, Tuple[Optional[Dict], int]] = {}

    @property
    def neighbors(self) -> List[str]:
        return list(self.factor_names)

    def on_start(self):
        value, cost = optimal_cost_value(self._variable, self.mode)
        self.value_selection(value, cost)
        self._send_to(self.factor_names)

    def on_pause(self, paused: bool):
        if not paused:
            self._prev.clear()
            self._send_to(self.factor_names)

    @register("max_sum")
    def _on_costs(self, sender, msg, t):
        self._costs[sender] = msg.costs
        value, cost = select_value(self._variable, self._costs, self.mode)
        if value != self.current_value:
            self.value_selection(value, cost)
        self.new_cycle()
        # Fire to every factor, sender included (see the factor-side
        # comment: damped iteration needs identical-message re-fires).
        self._send_to(self.factor_names)

    def _send_to(self, factor_names):
        for f_name in factor_names:
            costs_f = costs_for_factor(
                self._variable, f_name, self.factor_names, self._costs
            )
            send_damped(
                self, self._prev, f_name, costs_f,
                self.damping_nodes in ("vars", "both"),
                self.damping, self.stability,
            )


# --------------------------------------------------------------------- #
# A-DSA: clock-driven DSA (reference adsa.py:121-131 — re-evaluate on a
# periodic tick with the latest known neighbor values; no cycle sync).

AdsaValueMessage = message_type("adsa_value", ["value"])


class ADsaComputation(HypergraphComputation):
    """Asynchronous DSA: a periodic action on the agent clock
    re-evaluates the variable against whatever neighbor values have
    been seen so far; value messages carry no cycle bookkeeping.

    Anti-entropy: the current value is re-broadcast every
    ``REFRESH_TICKS`` ticks even when unchanged.  Value messages are
    only posted on change otherwise, so on a lossy link one dropped
    change can strand two neighbors in mutually-stale views where
    NEITHER side sees the real conflict and the solve silently freezes
    at a violated assignment; the periodic refresh guarantees views
    eventually heal (chaos battery, docs/resilience.md).  Receiving a
    value triggers no send, so the refresh adds bounded idempotent
    traffic, never a storm."""

    REFRESH_TICKS = 5

    def __init__(self, comp_def):
        super().__init__(comp_def)
        params = comp_def.algo.params
        self.probability = params.get("probability", 0.7)
        self.variant = params.get("variant", "B")
        self.period = params.get("period", 0.5)
        self.stop_cycle = params.get("stop_cycle", 0)
        self._ticks_since_broadcast = 0
        self._neighbor_values: Dict[str, Any] = {}
        if self.variant == "B":
            self._best_constraint_costs = {
                c.name: find_optimum(c, self.mode)
                for c in self.constraints
            }

    def on_start(self):
        if self._finish_no_neighbors():
            return
        self.random_value_selection()
        self.post_to_all_neighbors(AdsaValueMessage(self.current_value))
        self.add_periodic_action(self.period, self.tick)

    @register("adsa_value")
    def _on_value(self, sender, msg, t):
        self._neighbor_values[sender] = msg.value

    def tick(self):
        """Periodic re-evaluation (reference adsa.py:131)."""
        if not self._running or self.is_paused:
            return
        if len(self._neighbor_values) < len(self._neighbors):
            # Bootstrap: make sure everyone has our value.
            self.post_to_all_neighbors(
                AdsaValueMessage(self.current_value)
            )
            return
        asst = dict(self._neighbor_values)
        asst[self.name] = self.current_value
        best_values, best_cost = find_optimal(
            self._variable, self._neighbor_values, self.constraints,
            self.mode,
        )
        current_cost = assignment_cost(asst, self.constraints)
        delta = abs(current_cost - best_cost)
        changed = False
        if self.variant == "A":
            if delta > 0:
                changed = self._probabilistic_change(
                    best_cost, best_values
                )
        elif self.variant == "B":
            if delta > 0:
                changed = self._probabilistic_change(
                    best_cost, best_values
                )
            elif delta == 0 and self._exists_violated():
                if len(best_values) > 1 and \
                        self.current_value in best_values:
                    best_values.remove(self.current_value)
                changed = self._probabilistic_change(
                    best_cost, best_values
                )
        else:  # C
            if delta > 0:
                changed = self._probabilistic_change(
                    best_cost, best_values
                )
            elif delta == 0:
                if len(best_values) > 1 and \
                        self.current_value in best_values:
                    best_values.remove(self.current_value)
                changed = self._probabilistic_change(
                    best_cost, best_values
                )
        self.new_cycle()
        self._ticks_since_broadcast += 1
        if changed or self._ticks_since_broadcast >= self.REFRESH_TICKS:
            self._ticks_since_broadcast = 0
            self.post_to_all_neighbors(
                AdsaValueMessage(self.current_value)
            )
        if self.stop_cycle and self.cycle_count >= self.stop_cycle:
            self.finished()
            self.stop()

    def _probabilistic_change(self, best_cost, best_values) -> bool:
        if best_values and self.probability > random.random():
            value = random.choice(best_values)
            if value != self.current_value:
                self.value_selection(value, best_cost)
                return True
        return False

    def _exists_violated(self) -> bool:
        asst = dict(self._neighbor_values)
        asst[self.name] = self.current_value
        for c in self.constraints:
            cost = c(**{v.name: asst[v.name] for v in c.dimensions})
            if cost != self._best_constraint_costs[c.name]:
                return True
        return False


# --------------------------------------------------------------------- #
# Dynamic MaxSum (reference maxsum_dynamic.py:40-405 — the reference
# classes are documented there as broken post-refactor; these are working
# equivalents on the BSP computations above).


class DynamicFunctionFactorComputation(MaxSumFactorComputation):
    """MaxSum factor whose cost function can be swapped at run time.

    The new function must keep the same scope (reference
    maxsum_dynamic.py:84-100).  Under BSP semantics the swap is applied
    lazily: the new costs flow with the next cycle's messages (an
    immediate re-send would produce duplicate per-cycle messages, which
    the synchronous mixin rejects by design).
    """

    def change_factor_function(self, fn) -> None:
        old_names = {v.name for v in self.factor.dimensions}
        new_names = {v.name for v in fn.dimensions}
        if old_names != new_names:
            raise ValueError(
                "Dimensions must be the same when changing function in "
                f"DynamicFunctionFactorComputation: {old_names} vs "
                f"{new_names}"
            )
        self.factor = fn
        self.variables = fn.dimensions
        # Drop send-suppression state so updated costs are guaranteed to
        # go out on the next cycle.
        self._prev.clear()


class FactorWithReadOnlyVariableComputation(DynamicFunctionFactorComputation):
    """Factor whose relation depends on read-only (external/sensor)
    variables: subscribes to them and optimizes the relation sliced on
    their current values (reference maxsum_dynamic.py:113-186).
    """

    HANDLES_EXTERNALS = True

    def __init__(self, comp_def, relation=None, read_only_variables=None):
        super().__init__(comp_def)
        self._relation = relation if relation is not None else self.factor
        if read_only_variables is None:
            read_only_variables = [
                v for v in self._relation.dimensions
                if isinstance(v, _external_variable_type())
            ]
        self._read_only_variables = list(read_only_variables)
        ro_names = {v.name for v in self._read_only_variables}
        for v in self._read_only_variables:
            if v.name not in self._relation.scope_names:
                raise ValueError(
                    f"Read-only variable {v.name} must be in relation "
                    f"scope {self._relation.scope_names}"
                )
        self._read_only_values: Dict[str, Any] = {}
        # Until every sensor value is known, optimize a neutral relation
        # over the writable scope (reference :144-147).
        from pydcop_tpu.dcop.relations import NeutralRelation

        writable = [
            v for v in self._relation.dimensions if v.name not in ro_names
        ]
        self.factor = NeutralRelation(writable, name=self._relation.name)
        self.variables = writable

    @property
    def neighbors(self) -> List[str]:
        # Only writable variables take part in BSP cycles; read-only
        # (external) ones are plain-message subscriptions.
        return [v.name for v in self.variables]

    def on_start(self):
        for v in self._read_only_variables:
            # Plain (non-cycle) message: the external-variable
            # computation is not synchronous.
            MessagePassingComputation.post_msg(
                self, v.name, Message("subscribe", None)
            )

    @register("external_value")
    def _on_external_value(self, sender, msg, t):
        self._read_only_values[sender] = msg.content
        if len(self._read_only_values) < len(self._read_only_variables):
            return
        new_sliced = self._relation.slice(self._read_only_values)
        if set(new_sliced.scope_names) != {
            v.name for v in self.factor.dimensions
        } or not _same_costs(new_sliced, self.factor):
            self.change_factor_function(new_sliced)


class DynamicFactorComputation(MaxSumFactorComputation):
    """MaxSum factor whose function — and scope — can change at run
    time (reference maxsum_dynamic.py:188-350).

    Scope changes notify the affected variables with plain ``maxsum_add``
    / ``maxsum_remove`` messages so they adjust their factor lists.
    External variables in the scope are subscribed to automatically and
    sliced out of the optimized relation.
    """

    HANDLES_EXTERNALS = True

    def __init__(self, comp_def):
        super().__init__(comp_def)
        self._relation = self.factor
        self._external_variables = {
            v.name: v for v in self.factor.dimensions
            if isinstance(v, _external_variable_type())
        }
        if self._external_variables:
            values = {
                n: v.value for n, v in self._external_variables.items()
            }
            self.factor = self._relation.slice(values)
            self.variables = self.factor.dimensions

    @property
    def neighbors(self) -> List[str]:
        return [v.name for v in self.variables]

    def on_start(self):
        for name in self._external_variables:
            MessagePassingComputation.post_msg(
                self, name, Message("subscribe", None)
            )

    @register("external_value")
    def _on_external_value(self, sender, msg, t):
        if sender not in self._external_variables:
            return
        self._external_variables[sender].value = msg.content
        values = {
            n: v.value for n, v in self._external_variables.items()
        }
        new_sliced = self._relation.slice(values)
        if set(new_sliced.scope_names) != {
            v.name for v in self.factor.dimensions
        } or not _same_costs(new_sliced, self.factor):
            self.change_factor_function(new_sliced)

    def change_factor_function(self, fn) -> None:
        removed = [
            v for v in self.factor.dimensions
            if v.name not in fn.scope_names
        ]
        added = [
            v for v in fn.dimensions
            if v.name not in self.factor.scope_names
        ]
        self.factor = fn
        self.variables = fn.dimensions
        self._prev.clear()
        for v in removed:
            self._costs.pop(v.name, None)
            MessagePassingComputation.post_msg(
                self, v.name, Message("maxsum_remove", self.name)
            )
        for v in added:
            self._costs.setdefault(
                v.name, {d: 0 for d in v.domain}
            )
            MessagePassingComputation.post_msg(
                self, v.name, Message("maxsum_add", self.name)
            )


class DynamicFactorVariableComputation(MaxSumVariableComputation):
    """MaxSum variable that supports factors joining/leaving its scope
    via ``maxsum_add`` / ``maxsum_remove`` messages (reference
    maxsum_dynamic.py:352-405)."""

    @property
    def neighbors(self) -> List[str]:
        return list(self.factor_names)

    @register("maxsum_remove")
    def _on_remove_msg(self, sender, msg, t):
        factor_name = msg.content
        if factor_name not in self.factor_names:
            raise ValueError(
                f"Cannot remove factor {factor_name} from variable "
                f"{self.name}: not in {self.factor_names}"
            )
        self.factor_names.remove(factor_name)
        self._costs.pop(factor_name, None)
        self._prev.clear()
        # Sync-mixin bookkeeping: drop any message already collected
        # from the departed factor, then re-check completion — with the
        # neighbor set shrunk, the current cycle may already be full.
        self.current_cycle.pop(factor_name, None)
        value, cost = select_value(self._variable, self._costs, self.mode)
        self.value_selection(value, cost)
        self._maybe_switch_cycle()

    @register("maxsum_add")
    def _on_add_msg(self, sender, msg, t):
        factor_name = msg.content
        if factor_name not in self.factor_names:
            self.factor_names.append(factor_name)


def _external_variable_type():
    from pydcop_tpu.dcop.objects import ExternalVariable

    return ExternalVariable


def _same_costs(r1, r2) -> bool:
    """True when two relations over the same scope have identical cost
    tables (cheap dims are fine: dynamic factors stay small)."""
    import numpy as np

    try:
        return bool(np.array_equal(r1.to_array(), r2.to_array()))
    except MemoryError:
        return False


# --------------------------------------------------------------------- #
# DSA (asynchronous, cycle bookkeeping)

DsaMessage = message_type("dsa_value", ["value"])


class DsaComputation(VariableComputation):
    """DSA-A/B/C with per-cycle neighbor value maps (reference
    dsa.py:214-431)."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        params = comp_def.algo.params
        self.probability = params.get("probability", 0.7)
        self.variant = params.get("variant", "B")
        self.stop_cycle = params.get("stop_cycle", 0)
        self.constraints = list(comp_def.node.constraints)
        self._neighbors = [
            v.name for c in self.constraints for v in c.dimensions
            if v.name != self.name
        ]
        self._neighbors = list(dict.fromkeys(self._neighbors))
        if params.get("p_mode") == "arity":
            n_count = sum(len(c.dimensions) - 1 for c in self.constraints)
            if n_count:
                self.probability = 1.2 / n_count
        self.current_cycle: Dict[str, Any] = {}
        self.next_cycle: Dict[str, Any] = {}
        if self.variant == "B":
            self._best_constraint_costs = {
                c.name: find_optimum(c, self.mode) for c in self.constraints
            }

    @property
    def neighbors(self) -> List[str]:
        return self._neighbors

    def on_start(self):
        if not self._neighbors:
            value, cost = optimal_cost_value(self._variable, self.mode)
            self.value_selection(value, cost)
            self.finished()
            self.stop()
            return
        self.random_value_selection()
        self.post_to_all_neighbors(DsaMessage(self.current_value))
        self._evaluate_cycle()

    @register("dsa_value")
    def _on_value_msg(self, sender, msg, t):
        if not self._running:
            return
        if sender not in self.current_cycle:
            self.current_cycle[sender] = msg.value
            self._evaluate_cycle()
        else:
            self.next_cycle[sender] = msg.value

    def _evaluate_cycle(self):
        if len(self.current_cycle) < len(self._neighbors):
            return
        self.current_cycle[self.name] = self.current_value
        asst = dict(self.current_cycle)
        best_values, best_cost = find_optimal(
            self._variable, asst, self.constraints, self.mode
        )
        current_cost = assignment_cost(asst, self.constraints)
        delta = abs(current_cost - best_cost)

        if self.variant == "A":
            if delta > 0:
                self._probabilistic_change(best_cost, best_values)
        elif self.variant == "B":
            if delta > 0:
                self._probabilistic_change(best_cost, best_values)
            elif delta == 0 and self._exists_violated():
                if len(best_values) > 1 and \
                        self.current_value in best_values:
                    best_values.remove(self.current_value)
                self._probabilistic_change(best_cost, best_values)
        else:  # C
            if delta > 0:
                self._probabilistic_change(best_cost, best_values)
            elif delta == 0:
                if len(best_values) > 1 and \
                        self.current_value in best_values:
                    best_values.remove(self.current_value)
                self._probabilistic_change(best_cost, best_values)

        self.new_cycle()
        self.current_cycle, self.next_cycle = self.next_cycle, {}
        if self.stop_cycle and self.cycle_count >= self.stop_cycle:
            self.finished()
            self.stop()
            return
        self.post_to_all_neighbors(DsaMessage(self.current_value))

    def _probabilistic_change(self, best_cost, best_values):
        if self.probability > random.random():
            self.value_selection(random.choice(best_values), best_cost)

    def _exists_violated(self) -> bool:
        asst = dict(self.current_cycle)
        asst[self.name] = self.current_value
        for c in self.constraints:
            cost = c(**{v.name: asst[v.name] for v in c.dimensions})
            if cost != self._best_constraint_costs[c.name]:
                return True
        return False


# --------------------------------------------------------------------- #
# MGM (two-phase rounds)

MgmValueMessage = message_type("mgm_value", ["value"])
MgmGainMessage = message_type("mgm_gain", ["value", "random_nb"])


class MgmComputation(VariableComputation):
    """MGM rounds: value phase then gain phase, with postponed queues
    for early messages (reference mgm.py:213-609)."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        params = comp_def.algo.params
        self.break_mode = params.get("break_mode", "lexic")
        self.stop_cycle = params.get("stop_cycle", 0)
        self.constraints = list(comp_def.node.constraints)
        self._neighbors = list(dict.fromkeys(
            v.name for c in self.constraints for v in c.dimensions
            if v.name != self.name
        ))
        self._state = "values"
        self._neighbors_values: Dict[str, Any] = {}
        self._neighbors_gains: Dict[str, Tuple[float, float]] = {}
        self._postponed_values: List[Tuple] = []
        self._postponed_gains: List[Tuple] = []
        self._gain = 0.0
        self._new_value = None
        self._random_nb = 0.0

    @property
    def neighbors(self) -> List[str]:
        return self._neighbors

    def on_start(self):
        if not self._neighbors:
            value, cost = optimal_cost_value(self._variable, self.mode)
            self.value_selection(value, cost)
            self.finished()
            self.stop()
            return
        self.random_value_selection()
        self._send_value()

    def _send_value(self):
        self.new_cycle()
        if self.stop_cycle and self.cycle_count >= self.stop_cycle:
            self.finished()
            self.stop()
            return
        self.post_to_all_neighbors(MgmValueMessage(self.current_value))

    @register("mgm_value")
    def _on_value_msg(self, sender, msg, t):
        if self._state == "values":
            self._handle_value(sender, msg.value)
        else:
            self._postponed_values.append((sender, msg.value))

    def _handle_value(self, sender, value):
        self._neighbors_values[sender] = value
        if len(self._neighbors_values) < len(self._neighbors):
            return
        # All values in: compute current cost, best response and gain.
        asst = dict(self._neighbors_values)
        asst[self.name] = self.current_value
        current_cost = assignment_cost(asst, self.constraints)
        current_cost += self._variable.cost_for_val(self.current_value)
        self.value_selection(self.current_value, current_cost)

        best_values, best_cost = find_optimal(
            self._variable, self._neighbors_values, self.constraints,
            self.mode,
        )
        # Include own unary cost in the comparison:
        best_with_unary = None
        chosen = []
        for v in best_values:
            c = best_cost + self._variable.cost_for_val(v)
            if best_with_unary is None or c < best_with_unary:
                best_with_unary, chosen = c, [v]
            elif c == best_with_unary:
                chosen.append(v)
        self._gain = current_cost - best_with_unary
        if (self.mode == "min" and self._gain > 0) or (
            self.mode == "max" and self._gain < 0
        ):
            self._new_value = random.choice(chosen)
        else:
            self._new_value = self.current_value
        self._random_nb = random.random()
        self.post_to_all_neighbors(
            MgmGainMessage(self._gain, self._random_nb)
        )
        self._state = "gain"
        for sender2, msg2 in self._postponed_gains:
            self._handle_gain(sender2, msg2)
        self._postponed_gains.clear()

    @register("mgm_gain")
    def _on_gain_msg(self, sender, msg, t):
        if self._state == "gain":
            self._handle_gain(sender, msg)
        else:
            self._postponed_gains.append((sender, msg))

    def _handle_gain(self, sender, msg):
        self._neighbors_gains[sender] = (msg.value, msg.random_nb)
        if len(self._neighbors_gains) < len(self._neighbors):
            return
        max_gain = max(g for g, _ in self._neighbors_gains.values())
        if self._gain > max_gain:
            self.value_selection(
                self._new_value, self.current_cost - self._gain
            )
        elif self._gain == max_gain:
            if self.break_mode == "random":
                ties = sorted(
                    [
                        (rnd, name)
                        for name, (g, rnd) in
                        self._neighbors_gains.items()
                        if g == max_gain
                    ]
                    + [(self._random_nb, self.name)]
                )
            else:
                ties = sorted(
                    [
                        (name, name)
                        for name, (g, _) in
                        self._neighbors_gains.items()
                        if g == max_gain
                    ]
                    + [(self.name, self.name)]
                )
            if ties[0][1] == self.name:
                self.value_selection(
                    self._new_value, self.current_cost - self._gain
                )
        self._neighbors_gains.clear()
        self._neighbors_values.clear()
        self._state = "values"
        self._send_value()
        for sender2, value in self._postponed_values:
            self._handle_value(sender2, value)
        self._postponed_values.clear()


# --------------------------------------------------------------------- #
# NCBB (reference ncbb.py:139-350)


NcbbValueMessage = message_type("ncbb_value", ["value"])
# COST carries the subtree's separator (the ancestors appearing in any
# constraint of the subtree) up the tree: each node derives its
# children's separators from these reports, which lets the SEARCH
# phase project contexts before sending (see below).
NcbbCostMessage = message_type("ncbb_cost", ["cost", "separator"])
NcbbStopMessage = message_type("ncbb_stop", [])
# SEARCH-phase messages are BATCHED (the sync mixin allows one message
# per neighbor per cycle): a search message carries every context the
# parent wants this child's subtree optimum for; a results message
# carries every (context, optimal cost) answer ready this cycle.
NcbbSearchMessage = message_type("ncbb_search", ["contexts"])
NcbbResultsMessage = message_type("ncbb_results", ["results"])
NcbbFinalMessage = message_type("ncbb_final", ["context"])


class NcbbComputation(SynchronousComputationMixin, VariableComputation):
    """NCBB computation: synchronous phases over a DFS pseudo-tree.

    INIT phase per the reference (ncbb.py:216-330): the root picks a
    value and sends it down; every variable accumulates its ancestors'
    values, greedily optimizes against them, forwards its own value to
    descendants; leaves start COST messages whose subtree upper bounds
    accumulate back up to the root.  Two deliberate fixes over the
    reference: leaves send COST only to their tree parent (the
    reference posts to pseudo-parents too, which its own cost handler
    rejects), and termination is explicit.

    SEARCH phase — the part the reference stubs out (ncbb.py:341) —
    is a distributed AND/OR branch-and-bound over the pseudo-tree:

    - the root (then recursively every interior node) asks each tree
      child for its subtree's optimal cost under every candidate
      context ``{ancestor: value, ...}`` (one batched message per
      child per cycle; sibling subtrees and candidate values are
      explored CONCURRENTLY — NCBB's no-commitment concurrency);
    - every node charges exactly the constraints between itself and
      its (pseudo-)parents — in a DFS tree each constraint connects a
      node to an ancestor, so each is charged once, at its lower
      endpoint (same accounting as the INIT greedy and the engine
      path, algorithms/ncbb.py);
    - contexts are PROJECTED onto each child's separator (the
      ancestors appearing in any constraint of the child's subtree,
      reported upward on the INIT cost messages) before sending, so
      the number of distinct contexts a subtree explores is
      exponential in its separator width — DPOP's table width — not
      in the pseudo-tree depth;
    - values whose charged cost is already infinite (hard-constraint
      violation) are pruned before recursing; finite-cost pruning is
      deliberately NOT done because constraint costs may be negative,
      which would make bound-based pruning unsound;
    - subtree optima are memoized per context, so repeated contexts
      (and the final VALUE sweep) are answered from cache;
    - once the root knows its optimum it fixes its value and sends a
      FINAL context down the tree; each node looks up its memoized
      best value for that context, fixes it, extends the context, and
      forwards — after which the whole tree reports finished with the
      globally optimal assignment (asserted equal to DPOP on the
      golden fixtures).
    """

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        assert comp_def.algo.algo == "ncbb"
        node = comp_def.node
        self._parent = node.parent
        self._pseudo_parents = list(node.pseudo_parents)
        self._children = list(node.children)
        self._pseudo_children = list(node.pseudo_children)
        self._ancestors = self._pseudo_parents + (
            [self._parent] if self._parent else []
        )
        self._descendants = self._pseudo_children + self._children
        self.phase = "INIT"
        self._upper_bound = None
        self._constraints = []
        for c in node.constraints:
            if c.arity > 2:
                from pydcop_tpu.infrastructure.computations import (
                    ComputationException,
                )

                raise ComputationException(
                    f"Invalid constraint {c} with arity {c.arity} for "
                    f"variable {self.name}: NCBB only supports binary "
                    "constraints."
                )
            self._constraints.append(c)
        self._parents_values: Dict[str, Any] = {}
        self._children_costs: Dict[str, float] = {}
        # SEARCH-phase state.  Contexts are keyed on their projection
        # onto this node's separator; child bookkeeping is keyed on
        # (child, projection onto that child's separator).
        self._own_sep: frozenset = frozenset()
        self._child_sep: Dict[str, frozenset] = {}
        self._own_costs: Dict[tuple, Dict[Any, float]] = {}
        self._open_ctx: Dict[tuple, dict] = {}
        self._child_results: Dict[str, Dict[tuple, float]] = {}
        self._result_cache: Dict[tuple, float] = {}
        self._memo_value: Dict[tuple, Any] = {}
        self._outbox_search: Dict[str, list] = {}
        self._outbox_results: list = []
        self._requested: Dict[str, set] = {}

    @register("ncbb_value")
    def _on_value_registration(self, sender, msg, t):
        pass

    @register("ncbb_cost")
    def _on_cost_registration(self, sender, msg, t):
        pass

    @register("ncbb_stop")
    def _on_stop_registration(self, sender, msg, t):
        pass

    @register("ncbb_search")
    def _on_search_registration(self, sender, msg, t):
        pass

    @register("ncbb_results")
    def _on_results_registration(self, sender, msg, t):
        pass

    @register("ncbb_final")
    def _on_final_registration(self, sender, msg, t):
        pass

    @property
    def is_root(self) -> bool:
        return self._parent is None

    @property
    def is_leaf(self) -> bool:
        return len(self._children) == 0

    def _greedy_select(self):
        """Best value given the known ancestor values, counting the
        variable's own costs, unary constraints charged here, and every
        constraint whose scope is fully known (self + ancestors) — the
        same accounting as the engine path's unary[] + charged[]."""
        better = (
            (lambda a, b: a < b) if self.mode == "min"
            else (lambda a, b: a > b)
        )
        known = dict(self._parents_values)
        best_val, best_cost = None, None
        for val in self.variable.domain:
            cost = self.variable.cost_for_val(val)
            asst = {**known, self.name: val}
            for c in self._constraints:
                if all(s in asst for s in c.scope_names):
                    cost += c(**{s: asst[s] for s in c.scope_names})
            if best_cost is None or better(cost, best_cost):
                best_val, best_cost = val, cost
        return best_val, best_cost

    def on_start(self):
        if not self.is_root:
            return
        # Root: no ancestors, select greedily from own costs and send
        # down the tree (reference picks at random, :225; greedy is
        # deterministic and never worse).
        value, cost = self._greedy_select()
        self.value_selection(value)
        self._upper_bound = cost
        for child in self._descendants:
            self.post_msg(child, NcbbValueMessage(self.current_value))
        if self.is_leaf:
            # Isolated root: its greedy selection IS the optimum.
            self._finish_and_stop()

    def on_new_cycle(self, messages, cycle_id) -> Optional[List]:
        self._outbox_search = {}
        self._outbox_results = []
        for sender, (msg, t) in sorted(messages.items()):
            if msg.type == "ncbb_value":
                self._value_phase(sender, msg.value)
            elif msg.type == "ncbb_cost":
                self._cost_phase(sender, msg.cost, msg.separator)
            elif msg.type == "ncbb_stop":
                self._on_stop(sender)
            elif msg.type == "ncbb_search":
                if sender != self._parent:
                    from pydcop_tpu.infrastructure.computations import (
                        ComputationException,
                    )

                    raise ComputationException(
                        f"{self.name}: ncbb search from non-parent "
                        f"{sender}"
                    )
                for ctx in msg.contexts:
                    self._handle_search_request(ctx)
            elif msg.type == "ncbb_results":
                if sender not in self._children:
                    from pydcop_tpu.infrastructure.computations import (
                        ComputationException,
                    )

                    raise ComputationException(
                        f"{self.name}: ncbb results from non-child "
                        f"{sender}"
                    )
                self._handle_results(sender, msg.results)
            elif msg.type == "ncbb_final":
                if sender != self._parent:
                    from pydcop_tpu.infrastructure.computations import (
                        ComputationException,
                    )

                    raise ComputationException(
                        f"{self.name}: ncbb final from non-parent "
                        f"{sender}"
                    )
                self._handle_final(msg.context)
        out = []
        for child, ctxs in self._outbox_search.items():
            out.append((child, NcbbSearchMessage(ctxs)))
        if self._outbox_results and self._parent:
            out.append(
                (self._parent,
                 NcbbResultsMessage(self._outbox_results))
            )
        return out or None

    def _value_phase(self, sender: str, value):
        if sender not in self._ancestors:
            from pydcop_tpu.infrastructure.computations import (
                ComputationException,
            )

            raise ComputationException(
                f"{self.name}: ncbb value from non-ancestor {sender}"
            )
        self._parents_values[sender] = value
        if len(self._parents_values) < len(self._ancestors):
            return
        # Greedy selection against known ancestors (reference :286-300,
        # plus own/unary costs so the bound matches a real assignment).
        value, cost = self._greedy_select()
        self.value_selection(value)
        self._upper_bound = cost
        for child in self._descendants:
            self.post_msg(child, NcbbValueMessage(self.current_value))
        if self.is_leaf and self._parent:
            self._own_sep = self._constrained_ancestors()
            self.post_msg(
                self._parent,
                NcbbCostMessage(cost, sorted(self._own_sep)),
            )

    def _cost_phase(self, sender: str, cost: float, separator):
        if sender not in self._children:
            from pydcop_tpu.infrastructure.computations import (
                ComputationException,
            )

            raise ComputationException(
                f"{self.name}: ncbb cost from non-child {sender}"
            )
        self._children_costs[sender] = cost
        self._child_sep[sender] = frozenset(separator)
        self._upper_bound += cost
        if len(self._children_costs) < len(self._children):
            return
        self.phase = "SEARCH"
        self._own_sep = frozenset(
            self._constrained_ancestors().union(*self._child_sep.values())
            - {self.name}
        )
        if not self.is_root:
            self.post_msg(
                self._parent,
                NcbbCostMessage(
                    self._upper_bound, sorted(self._own_sep)),
            )
        else:
            # Root holds the global INIT bound: start the search with
            # the empty context.
            self._handle_search_request({})

    def _finish_and_stop(self):
        for child in self._children:
            self.post_msg(child, NcbbStopMessage())
        self.finished()

    def _on_stop(self, sender: str):
        self.phase = "SEARCH"
        for child in self._children:
            self.post_msg(child, NcbbStopMessage())
        self.finished()

    # -- SEARCH phase -------------------------------------------------- #

    @staticmethod
    def _key(ctx: dict) -> tuple:
        return tuple(sorted(ctx.items()))

    def _constrained_ancestors(self) -> set:
        """Ancestors appearing in this variable's own constraints."""
        names = set()
        for c in self._constraints:
            names.update(c.scope_names)
        names.discard(self.name)
        return names

    def _project(self, ctx: dict, sep: frozenset) -> dict:
        return {k: v for k, v in ctx.items() if k in sep}

    def _charged_cost(self, ctx: dict, val) -> float:
        """Own + unary costs plus every constraint between this
        variable and an ancestor (all evaluable from ctx)."""
        cost = self.variable.cost_for_val(val)
        asst = {**ctx, self.name: val}
        for c in self._constraints:
            if all(s in asst for s in c.scope_names):
                cost += c(**{s: asst[s] for s in c.scope_names})
        return cost

    def _pruned(self, cost: float) -> bool:
        """Hard-violation pruning only: finite-bound pruning would be
        unsound with negative constraint costs."""
        if self.mode == "min":
            return cost == float("inf")
        return cost == float("-inf")

    def _child_key(self, ctx: dict, val, child: str):
        """(projected context, key) a child must solve when I take
        ``val`` under my (already-projected) context ``ctx``."""
        child_ctx = self._project(
            {**ctx, self.name: val}, self._child_sep[child]
        )
        return child_ctx, self._key(child_ctx)

    def _handle_search_request(self, ctx: dict):
        """``ctx`` arrives projected onto my separator (the parent
        projects before sending, using the separator I reported on my
        INIT cost message)."""
        key = self._key(ctx)
        if key in self._result_cache:
            self._queue_result(ctx, self._result_cache[key])
            return
        if key in self._open_ctx:
            return  # already being explored
        own = {
            val: self._charged_cost(ctx, val)
            for val in self.variable.domain
        }
        self._own_costs[key] = own
        self._open_ctx[key] = ctx
        if self.is_leaf:
            self._resolve(key)
            return
        for val in self.variable.domain:
            if self._pruned(own[val]):
                continue
            for child in self._children:
                child_ctx, ckey = self._child_key(ctx, val, child)
                requested = self._requested.setdefault(child, set())
                if ckey in requested:
                    continue
                requested.add(ckey)
                self._outbox_search.setdefault(child, []).append(
                    child_ctx)
        self._maybe_resolve(key)

    def _handle_results(self, sender: str, results):
        for child_ctx, cost in results:
            self._child_results.setdefault(sender, {})[
                self._key(child_ctx)] = cost
        # Projection makes open-context counts small (bounded by the
        # separator-width cross product), so just re-check them all.
        for key in list(self._open_ctx):
            self._maybe_resolve(key)

    def _maybe_resolve(self, key: tuple):
        """Resolve an open context once every non-pruned value has all
        children's subtree optima (or everything was pruned)."""
        if key not in self._open_ctx:
            return
        ctx = self._open_ctx[key]
        own = self._own_costs[key]
        for val in self.variable.domain:
            if self._pruned(own[val]):
                continue
            for child in self._children:
                _, ckey = self._child_key(ctx, val, child)
                if ckey not in self._child_results.get(child, {}):
                    return
        self._resolve(key)

    def _resolve(self, key: tuple):
        better = (
            (lambda a, b: a < b) if self.mode == "min"
            else (lambda a, b: a > b)
        )
        ctx = self._open_ctx.pop(key)
        own = self._own_costs.pop(key)
        best_val, best_cost = None, None
        for val in self.variable.domain:
            cost = own[val]
            if not self.is_leaf and not self._pruned(cost):
                for child in self._children:
                    _, ckey = self._child_key(ctx, val, child)
                    cost += self._child_results[child][ckey]
            if best_cost is None or better(cost, best_cost):
                best_val, best_cost = val, cost
        self._result_cache[key] = best_cost
        self._memo_value[key] = best_val
        if self.is_root:
            self._finish_search(ctx, best_val)
        else:
            self._queue_result(ctx, best_cost)

    def _queue_result(self, ctx: dict, cost: float):
        self._outbox_results.append([ctx, cost])

    def _finish_search(self, ctx: dict, best_val):
        """Fix the optimal value and propagate the final context down
        the tree (each node answers from its memo after projecting)."""
        self.value_selection(best_val)
        final_ctx = {**ctx, self.name: best_val}
        for child in self._children:
            self.post_msg(child, NcbbFinalMessage(final_ctx))
        self.finished()

    def _handle_final(self, context: dict):
        """The final context accumulates every chosen value on the
        path; my searched key is its projection onto my separator."""
        key = self._key(self._project(context, self._own_sep))
        best_val = self._memo_value.get(key)
        if best_val is None:
            # Never searched (subtree fully pruned upstream): fall
            # back to the INIT greedy value already selected.
            best_val = self.current_value
        self._finish_search(context, best_val)


# --------------------------------------------------------------------- #
# Registry


# Every algorithm module has an agent-mode (message-passing)
# computation (reference parity: all 14 reference algorithms are
# distributed computations).
AGENT_MODE_ALGOS = frozenset(
    {"maxsum", "amaxsum", "maxsum_dynamic", "dsa", "adsa", "dsatuto",
     "mgm", "ncbb", "dpop", "syncbb", "mgm2", "dba", "gdba",
     "mixeddsa"}
)


def has_agent_computation(algo_name: str) -> bool:
    return algo_name in AGENT_MODE_ALGOS


def build(algo_name: str, comp_def):
    from pydcop_tpu.computations_graph.factor_graph import (
        FactorComputationNode,
        VariableComputationNode,
    )
    from pydcop_tpu.infrastructure.agent_breakout import (
        DbaComputation,
        GdbaComputation,
        MixedDsaComputation,
        Mgm2Computation,
    )
    from pydcop_tpu.infrastructure.agent_search import (
        DpopComputation,
        SyncBBComputation,
    )

    if algo_name == "maxsum":
        node = comp_def.node
        if isinstance(node, FactorComputationNode):
            return MaxSumFactorComputation(comp_def)
        if isinstance(node, VariableComputationNode):
            return MaxSumVariableComputation(comp_def)
        raise TypeError(f"Unsupported node for maxsum: {node}")
    if algo_name == "amaxsum":
        node = comp_def.node
        if isinstance(node, FactorComputationNode):
            return AMaxSumFactorComputation(comp_def)
        if isinstance(node, VariableComputationNode):
            return AMaxSumVariableComputation(comp_def)
        raise TypeError(f"Unsupported node for amaxsum: {node}")
    if algo_name == "maxsum_dynamic":
        node = comp_def.node
        if isinstance(node, FactorComputationNode):
            return DynamicFactorComputation(comp_def)
        if isinstance(node, VariableComputationNode):
            return DynamicFactorVariableComputation(comp_def)
        raise TypeError(f"Unsupported node for maxsum_dynamic: {node}")
    if algo_name in ("dsa", "dsatuto"):
        return DsaComputation(comp_def)
    if algo_name == "adsa":
        return ADsaComputation(comp_def)
    if algo_name == "mgm":
        return MgmComputation(comp_def)
    if algo_name == "ncbb":
        return NcbbComputation(comp_def)
    if algo_name == "dpop":
        return DpopComputation(comp_def)
    if algo_name == "syncbb":
        return SyncBBComputation(comp_def)
    if algo_name == "mgm2":
        return Mgm2Computation(comp_def)
    if algo_name == "dba":
        return DbaComputation(comp_def)
    if algo_name == "gdba":
        return GdbaComputation(comp_def)
    if algo_name == "mixeddsa":
        return MixedDsaComputation(comp_def)
    raise NotImplementedError(
        f"No agent-mode computation for algorithm {algo_name!r} yet"
    )

"""Extra battery over utils/expressionfunction.py beyond
test_expressionfunction.py: function-body form, scope modules,
external sources, partial chains, and wire round-trips."""

import pytest

from pydcop_tpu.utils.expressionfunction import ExpressionFunction
from pydcop_tpu.utils.simple_repr import from_repr, simple_repr


class TestExpressionForm:
    def test_positional_args_follow_discovery_order(self):
        f = ExpressionFunction("a - b")
        assert f(5, 3) == 2   # names discovered in load order: a, b

    def test_math_module_available(self):
        f = ExpressionFunction("math.floor(x / 2)")
        assert f(x=5) == 2
        assert list(f.variable_names) == ["x"]  # math is not a var

    def test_builtins_not_variables(self):
        f = ExpressionFunction("max(a, abs(b))")
        assert sorted(f.variable_names) == ["a", "b"]
        assert f(a=1, b=-5) == 5

    def test_conditional_expression(self):
        f = ExpressionFunction("0 if v1 == v2 else 10")
        assert f(v1=1, v2=1) == 0
        assert f(v1=1, v2=2) == 10

    def test_name_property_is_expression(self):
        f = ExpressionFunction("a + 1")
        assert f.__name__ == "a + 1"


class TestBodyForm:
    BODY = """
if x > 1:
    return x * 10
return 0
"""

    def test_return_body_compiles(self):
        f = ExpressionFunction(self.BODY)
        assert f(x=2) == 20
        assert f(x=0) == 0

    def test_body_variable_discovery(self):
        f = ExpressionFunction(self.BODY)
        assert list(f.variable_names) == ["x"]

    def test_body_with_local_assignment(self):
        f = ExpressionFunction("""
tmp = a * 2
return tmp + b
""")
        # tmp is assigned, so only a and b are inputs
        assert sorted(f.variable_names) == ["a", "b"]
        assert f(a=2, b=1) == 5


class TestPartial:
    def test_partial_freezes_and_shrinks_names(self):
        f = ExpressionFunction("a + b + c")
        g = f.partial(b=10)
        assert sorted(g.variable_names) == ["a", "c"]
        assert g(a=1, c=2) == 13
        assert g.fixed_vars == {"b": 10}

    def test_partial_chain(self):
        f = ExpressionFunction("a + b + c")
        h = f.partial(b=10).partial(c=100)
        assert list(h.variable_names) == ["a"]
        assert h(a=1) == 111

    def test_partial_keeps_expression(self):
        f = ExpressionFunction("a + b").partial(b=1)
        assert f.expression == "a + b"

    def test_original_unchanged_by_partial(self):
        f = ExpressionFunction("a + b")
        f.partial(b=1)
        assert sorted(f.variable_names) == ["a", "b"]


class TestExternalSource:
    def test_source_module_callable(self, tmp_path):
        src = tmp_path / "ext.py"
        src.write_text("def double(v):\n    return v * 2\n")
        f = ExpressionFunction("source.double(v1)",
                               source_file=str(src))
        assert f(v1=4) == 8
        # "source" is scope, not a variable
        assert list(f.variable_names) == ["v1"]

    def test_missing_source_file_raises(self):
        with pytest.raises(FileNotFoundError):
            ExpressionFunction("source.f(v)", source_file="/nope.py")


class TestEqualityAndWire:
    def test_equality_on_expression_and_fixed(self):
        assert ExpressionFunction("a+1") == ExpressionFunction("a+1")
        assert ExpressionFunction("a+1") != ExpressionFunction("a+2")
        assert (ExpressionFunction("a+b").partial(b=1)
                != ExpressionFunction("a+b"))

    def test_hashable(self):
        s = {ExpressionFunction("a+1"), ExpressionFunction("a+1")}
        assert len(s) == 1

    def test_wire_roundtrip(self):
        f = ExpressionFunction("a * b").partial(b=3)
        f2 = from_repr(simple_repr(f))
        assert f2 == f
        assert f2(a=2) == 6

    def test_wire_roundtrip_body_form(self):
        f = ExpressionFunction("return x + 1")
        f2 = from_repr(simple_repr(f))
        assert f2(x=1) == 2

"""pydcop_tpu — a TPU-native DCOP framework.

A from-scratch re-design of pyDCOP's capabilities (reference:
/root/reference, bladeXue/pyDcop) built TPU-first on JAX/XLA:

- the *problem model* (domains, variables, constraints, agents, YAML
  formats) is pure Python and format-compatible with the reference
  (``/root/reference/docs/usage/file_formats/dcop_format.yml``);
- the *execution engine* compiles a DCOP + computation graph into dense,
  padded, bucketed arrays and runs message-passing algorithms as jitted
  bulk-synchronous supersteps (``lax.scan`` over a functional state), with
  sharding over a ``jax.sharding.Mesh`` replacing the reference's
  thread-per-agent runtime (reference: pydcop/infrastructure/agents.py:78);
- an agent-mode runtime (threads + in-process / HTTP transports) is kept
  for parity with the reference's distributed deployment model.
"""

__version__ = "0.1.0"


def solve(*args, **kwargs):
    """Shortcut for :func:`pydcop_tpu.api.solve` (lazy import to keep
    modeling-only imports light)."""
    from pydcop_tpu.api import solve as _solve

    return _solve(*args, **kwargs)

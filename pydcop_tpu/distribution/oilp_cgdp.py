"""oilp_cgdp: optimal ILP for the Constraint-Graph Distribution Problem.

Reference parity: pydcop/distribution/oilp_cgdp.py.
"""

from pydcop_tpu.distribution.ilp_compref import (  # noqa: F401
    distribute,
    distribution_cost,
)

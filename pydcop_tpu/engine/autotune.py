"""Aggregation autotuner: measure, don't guess.

The variable-side aggregation is the op that dominates the superstep
past the ~100k-var scale cliff (BENCH_TPU.md), and the best strategy
is backend- and shape-dependent: scatter wins everywhere on CPU,
while on TPU the scatter-add serializes row updates and the dense
ell gather is the candidate (docs/performance.md, round-5 on-chip
A/B).  A manual ``aggregation=`` flag nobody tunes leaves that
performance on the table; ``aggregation='auto'`` replaces it with a
per-graph measurement: micro-time the candidate strategies on the
*actual* compiled graph (same bucket shapes, same edge distribution,
random message payloads), pick the winner, and record the decision
in ``DeviceRunResult.metrics``.

Constraints the measurement respects (never violated, never silently
worked around):

- **mesh**: sharded graphs always use scatter (shard_graph drops the
  agg arrays) — callers resolve that before ever reaching here
  (engine/compile.validated_aggregation), and :func:`autotune_aggregation`
  re-checks ``pad_to``;
- **hub guard**: the ell builder refuses degree-skewed graphs whose
  padded lists would explode ([V+1, K] with K = max degree); the
  autotuner catches that refusal and drops ell from the candidate
  set instead of OOMing;
- **numerics**: "boundary" is timed for the record but NEVER
  selected — its f32 prefix sum cancels catastrophically at exactly
  the scale it targets (measured, docs/performance.md), which is why
  the maxsum param validation does not offer it either.

Decisions persist in a JSON cache keyed by (backend, graph shape):
re-serving a same-shaped problem skips the micro-benchmark entirely.
Default location ``~/.cache/pydcop_tpu/agg_autotune.json``
(``PYDCOP_AGG_AUTOTUNE_CACHE`` overrides; an unwritable path degrades
to measuring every time, never to failing the solve).
"""

import json
import logging
import os
import tempfile
import threading
from typing import Any, Dict, Optional

import numpy as np

from pydcop_tpu.engine.compile import (
    AGGREGATIONS,
    CompiledFactorGraph,
    build_aggregation_arrays,
)

logger = logging.getLogger("pydcop.engine.autotune")

# Strategies a solve may actually run with.  "boundary" is excluded
# on numerics (see module docstring), matching the algo-param policy.
SELECTABLE = ("scatter", "sorted", "ell")

_CACHE_VERSION = 1


def cache_path() -> str:
    env = os.environ.get("PYDCOP_AGG_AUTOTUNE_CACHE")
    if env:
        return env
    return os.path.join(
        os.path.expanduser("~"), ".cache", "pydcop_tpu",
        "agg_autotune.json",
    )


def shape_key(backend: str, n_vars: int, dmax: int,
              bucket_shapes, max_degree: int) -> str:
    """Stable string key for "same-shaped problem": backend + var/
    domain counts + per-bucket (arity, rows) + the max variable
    degree.  Cost values are deliberately absent — the aggregation op
    never reads them.  The degree term matters: the ell hub guard
    trips on max degree, so two graphs with identical bucket shapes
    but different degree skew must NOT share a cached 'ell' decision
    (a replay onto the hub-skewed twin would refuse to build).
    ``bucket_shapes`` is an iterable of (arity, rows), arity-sorted.
    """
    buckets = ";".join(f"{a}x{r}" for a, r in bucket_shapes)
    return (
        f"v{_CACHE_VERSION}|{backend}|V{n_vars}|D{dmax}"
        f"|{buckets}|K{max_degree}"
    )


def graph_max_degree(graph: CompiledFactorGraph) -> int:
    """Max real-variable degree over the flattened edge slots (the
    quantity the ell hub guard trips on; sentinel edges excluded)."""
    counts = np.zeros(graph.n_vars + 1, dtype=np.int64)
    for b in graph.buckets:
        counts += np.bincount(
            b.var_ids.reshape(-1), minlength=graph.n_vars + 1)
    return int(counts[:-1].max()) if graph.n_vars else 0


def graph_shape_key(graph: CompiledFactorGraph,
                    backend: Optional[str] = None) -> str:
    if backend is None:
        import jax

        backend = jax.default_backend()
    return shape_key(
        backend, graph.n_vars, graph.dmax,
        [(b.var_ids.shape[1], b.var_ids.shape[0])
         for b in graph.buckets],
        graph_max_degree(graph),
    )


def dcop_shape_key(dcop, backend: Optional[str] = None) -> str:
    """Shape key computed from a DCOP directly (variable/domain
    counts, per-arity factor counts, max scope degree) — identical to
    :func:`graph_shape_key` of its compiled graph at ``pad_to=1``, so
    persisted decisions replay BEFORE compiling."""
    if backend is None:
        import jax

        backend = jax.default_backend()
    variables = list(dcop.variables.values())
    counts: Dict[int, int] = {}
    degree: Dict[str, int] = {}
    for c in dcop.constraints.values():
        if c.arity == 0:
            continue
        counts[c.arity] = counts.get(c.arity, 0) + 1
        for v in c.dimensions:
            degree[v.name] = degree.get(v.name, 0) + 1
    return shape_key(
        backend,
        len(variables),
        max((len(v.domain) for v in variables), default=1),
        sorted(counts.items()),
        max(degree.values(), default=0),
    )


def cached_choice(key: str,
                  cache_file: Optional[str] = None) -> Optional[str]:
    """Replay a persisted decision for ``key`` (None on miss/invalid)
    — lets callers resolve the strategy BEFORE compiling, so the
    winner's layout arrays come out of the compile-time structure
    cache instead of being rebuilt per solve."""
    cached = _load_cache(cache_file or cache_path()).get(key)
    if isinstance(cached, dict) \
            and cached.get("aggregation") in SELECTABLE:
        return cached["aggregation"]
    return None


def _load_cache(path: str) -> Dict[str, Any]:
    try:
        with open(path) as f:
            data = json.load(f)
        if isinstance(data, dict):
            return data
    except (OSError, ValueError):
        pass
    return {}


def _store_cache(path: str, data: Dict[str, Any]) -> None:
    """Atomic merge-and-write; failure logs and moves on (the cache
    is an optimization, not a dependency)."""
    try:
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        merged = _load_cache(path)
        merged.update(data)
        fd, tmp = tempfile.mkstemp(
            dir=directory, prefix=".autotune_", suffix=".json")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(merged, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except OSError as e:
        logger.warning("autotune cache not persisted to %s: %s",
                       path, e)


def apply_aggregation(graph: CompiledFactorGraph,
                      aggregation: str) -> CompiledFactorGraph:
    """Rebuild a compiled graph's agg_* arrays for ``aggregation``
    (structure-only: costs and var_ids are shared, not copied)."""
    perm, sorted_seg, starts, ends, ell = build_aggregation_arrays(
        graph.buckets, graph.n_vars + 1, aggregation
    )
    return graph._replace(
        agg_perm=perm, agg_sorted_seg=sorted_seg,
        agg_starts=starts, agg_ends=ends, agg_ell=ell,
    )


def _time_strategy(graph: CompiledFactorGraph, f2v, reps: int,
                   ) -> float:
    """Median seconds for one aggregation pass, warmed (compile
    excluded), honest completion via engine.timing.sync."""
    import jax

    from pydcop_tpu.engine.timing import sync, timed_call
    from pydcop_tpu.ops.maxsum import aggregate_beliefs

    fn = jax.jit(lambda g, m: aggregate_beliefs(g, m)[1])
    placed = jax.device_put(graph)
    sync(fn(placed, f2v))  # compile + warm
    times = [timed_call(fn, placed, f2v)[1] for _ in range(reps)]
    return float(np.median(times))


def autotune_aggregation(graph: CompiledFactorGraph, *,
                         pad_to: int = 1,
                         reps: int = 3,
                         use_cache: bool = True,
                         cache_file: Optional[str] = None,
                         ) -> Dict[str, Any]:
    """Pick the aggregation strategy for ``graph`` by measurement.

    Returns ``{"aggregation", "aggregation_source",
    "aggregation_timings_ms", "aggregation_key"}`` — the dict engines
    merge into ``DeviceRunResult.metrics``.  ``aggregation_source``
    is one of:

    - ``"mesh"``: sharded run, scatter is the only valid strategy
      (nothing measured);
    - ``"empty"``: no factor edges, nothing to aggregate;
    - ``"cache"``: decision replayed from the JSON shape cache;
    - ``"measured"``: micro-benchmarked on this process's backend.

    Timings are reported for all four named strategies where
    measurable (``None`` where not: hub-guard refusals, mesh runs);
    selection only ever happens among :data:`SELECTABLE`.
    """
    import jax

    backend = jax.default_backend()
    key = graph_shape_key(graph, backend)
    timings: Dict[str, Optional[float]] = {
        s: None for s in AGGREGATIONS}
    if pad_to > 1:
        return {
            "aggregation": "scatter",
            "aggregation_source": "mesh",
            "aggregation_timings_ms": timings,
            "aggregation_key": key,
        }
    n_edges = sum(
        int(np.prod(b.var_ids.shape)) for b in graph.buckets)
    if n_edges == 0:
        return {
            "aggregation": "scatter",
            "aggregation_source": "empty",
            "aggregation_timings_ms": timings,
            "aggregation_key": key,
        }

    path = cache_file or cache_path()
    if use_cache:
        cached = _load_cache(path).get(key)
        if (isinstance(cached, dict)
                and cached.get("aggregation") in SELECTABLE):
            return {
                "aggregation": cached["aggregation"],
                "aggregation_source": "cache",
                "aggregation_timings_ms": cached.get(
                    "aggregation_timings_ms", timings),
                "aggregation_key": key,
            }

    # Random message payloads: the aggregation's cost is layout- and
    # index-driven, value-independent — any dense payload measures it.
    # Placed on device ONCE: host-resident payloads would add the
    # same multi-MB host→device transfer to every rep of every
    # strategy, drowning the kernel-time differences being measured.
    rng = np.random.default_rng(0)
    d = graph.dmax
    f2v = jax.device_put(tuple(
        rng.standard_normal(
            b.var_ids.shape + (d,)).astype(np.float32)
        for b in graph.buckets
    ))
    notes: Dict[str, str] = {}
    for strategy in AGGREGATIONS:
        try:
            variant = apply_aggregation(graph, strategy)
        except ValueError as e:
            # The hub guard refusing ell (or any builder refusal):
            # record why, drop the candidate.
            notes[strategy] = str(e).split(":")[0]
            continue
        try:
            timings[strategy] = _time_strategy(variant, f2v, reps)
        except Exception as e:  # pragma: no cover - backend-specific
            notes[strategy] = f"{type(e).__name__}"
            logger.warning("autotune: %s failed to run: %s",
                           strategy, e)

    candidates = {
        s: t for s, t in timings.items()
        if s in SELECTABLE and t is not None
    }
    # Deterministic tie-break: strategy order in SELECTABLE (scatter
    # first — the parity default) wins exact ties.
    choice = min(
        candidates,
        key=lambda s: (candidates[s], SELECTABLE.index(s)),
    ) if candidates else "scatter"
    timings_ms = {
        s: (None if t is None else round(t * 1e3, 4))
        for s, t in timings.items()
    }
    result = {
        "aggregation": choice,
        "aggregation_source": "measured",
        "aggregation_timings_ms": timings_ms,
        "aggregation_key": key,
    }
    if notes:
        result["aggregation_notes"] = notes
    if use_cache:
        _store_cache(path, {key: {
            "aggregation": choice,
            "aggregation_timings_ms": timings_ms,
            "backend": backend,
        }})
    return result


# --------------------------------------------------------------------- #
# Whole-algorithm portfolio racer (ISSUE 10): the micro-timing pattern
# above, generalized from aggregation strategies to whole kernels.
# Each candidate races a short budget of cycles ON THE REAL COMPILED
# GRAPH; the winner is the fastest candidate whose final cost reaches
# the best cost any candidate achieved (within tolerance) — i.e. the
# decision optimizes time-to-target-cost, not cycles/sec.  Decisions
# persist in the same JSON shape cache as the aggregation autotuner
# (distinct key prefix), so a same-structure re-solve replays with
# zero measurement — api.solve(algo="auto") and the serving dispatch
# path both consume the cached decision.

# Candidate order IS the deterministic tie-break (parity-default
# maxsum first).  "dpop" (exact inference, ISSUE 17) is a *conditional*
# candidate: it only races when the caller supplies its runner via
# ``extra_runners`` — which :func:`dpop_portfolio_runner` refuses to
# build past the width ceiling, so wide structures never pay an exact
# attempt and always resolve to an iterative winner.
PORTFOLIO_CANDIDATES = (
    "maxsum", "maxsum_prune", "maxsum_decim", "dsa", "mgm", "gdba",
    "dpop",
)

# Winner -> (algorithm name, extra algo_params) for api.solve.
PORTFOLIO_PARAMS = {
    "maxsum": ("maxsum", {}),
    "maxsum_prune": ("maxsum", {"prune": True}),
    "maxsum_decim": ("maxsum", {"decimation": 10}),
    "dsa": ("dsa", {}),
    "mgm": ("mgm", {}),
    "gdba": ("gdba", {}),
    "dpop": ("dpop", {}),
}

# Width gate for *racing* exact inference: deliberately far below
# ops/dpop.MAX_NODE_ELEMENTS — the race is a latency probe, and a
# hypercube this side of the gate solves in the same ballpark as a
# 60-cycle iterative race leg.  Past it, DPOP may still be reachable
# explicitly (algo="dpop"), just not auto-raced.
DPOP_RACE_MAX_ELEMENTS = 2 ** 20

_PORTFOLIO_PREFIX = f"portfolio-v{_CACHE_VERSION}|"

# Candidates whose final cost must come within this fraction of the
# best achieved cost (plus an absolute epsilon for zero-cost targets)
# to be eligible on time.
_PORTFOLIO_COST_TOL = 0.02
_PORTFOLIO_RACE_CYCLES = 60


def portfolio_key(shape: str) -> str:
    return _PORTFOLIO_PREFIX + shape


def dcop_portfolio_key(dcop, backend: Optional[str] = None) -> str:
    return portfolio_key(dcop_shape_key(dcop, backend))


def cached_portfolio_choice(key: str,
                            cache_file: Optional[str] = None
                            ) -> Optional[str]:
    """Replay a persisted portfolio decision (None on miss/invalid)."""
    cached = _load_cache(cache_file or cache_path()).get(key)
    if isinstance(cached, dict) \
            and cached.get("algo") in PORTFOLIO_CANDIDATES:
        return cached["algo"]
    return None


# Public alias: consumers of the cached race timings (the serving
# envelope cost model scales them to a request's cycle budget) need
# the cycle count the race actually ran.
PORTFOLIO_RACE_CYCLES = _PORTFOLIO_RACE_CYCLES


def cached_portfolio_timing_ms(key: str,
                               cache_file: Optional[str] = None,
                               data: Optional[Dict[str, Any]] = None
                               ) -> Optional[float]:
    """The persisted portfolio WINNER's measured race time (ms over
    :data:`PORTFOLIO_RACE_CYCLES` cycles of the real compiled graph)
    for ``key`` — a free per-structure solve-time prior.  The serving
    scheduler's envelope pack-vs-solo cost model consumes it
    (serving/binning.solve_prior_ms): a structure the portfolio racer
    ever measured gets a real number instead of a cells*cycles
    estimate, at zero measurement cost on the serving path.  None on
    miss/invalid/unmeasured-winner.

    ``data`` is an already-loaded cache dict (:func:`_load_cache`) —
    the serving flush planner loads the JSON ONCE per flush and
    resolves every group member against it, instead of paying one
    file read per member."""
    cached = (data if data is not None
              else _load_cache(cache_file or cache_path())).get(key)
    if isinstance(cached, dict) \
            and cached.get("algo") in PORTFOLIO_CANDIDATES:
        timing = (cached.get("portfolio_timings_ms")
                  or {}).get(cached["algo"])
        if isinstance(timing, (int, float)) and timing > 0:
            return float(timing)
    return None


def _portfolio_runners(graph: CompiledFactorGraph, race_cycles: int,
                       meta=None):
    """Build (name -> zero-arg callable returning final cost) over the
    placed graph.  Each callable is self-contained and warmed by its
    first invocation; the caller times the second.

    ``meta`` (a FactorGraphMeta) makes the mgm/gdba race use the SAME
    lexical-name tie-break ranks the deployed winner would
    (algorithms/mgm.lexic_ranks) — a race with different tie-breaks
    would persist a decision about a trajectory the winner never
    runs.  Without meta, index order with the +inf sentinel is the
    closest stand-in."""
    import jax
    import jax.numpy as jnp
    from functools import partial

    from pydcop_tpu.ops import dsa as dsa_ops
    from pydcop_tpu.ops import gdba as gdba_ops
    from pydcop_tpu.ops import maxsum as maxsum_ops
    from pydcop_tpu.ops import mgm as mgm_ops
    from pydcop_tpu.ops.localsearch import assignment_cost

    placed = jax.device_put(graph)
    n_rows = graph.var_costs.shape[0]
    if meta is not None:
        from pydcop_tpu.algorithms.mgm import lexic_ranks

        ranks = jnp.asarray(lexic_ranks(meta))
    else:
        ranks = jnp.concatenate([
            jnp.arange(n_rows - 1, dtype=jnp.float32),
            jnp.asarray([jnp.inf], dtype=jnp.float32),
        ])

    def cost_of(values):
        full = jnp.concatenate(
            [values, jnp.zeros((1,), values.dtype)])
        return assignment_cost(placed, full)

    def maxsum_runner(prune: bool):
        fn = jax.jit(lambda g: cost_of(maxsum_ops.run_maxsum(
            g, race_cycles, stop_on_convergence=False,
            prune=prune)[1]))
        return lambda: float(fn(placed))

    def decim_runner():
        half = max(race_cycles // 2, 1)
        first = jax.jit(lambda g: maxsum_ops.run_maxsum(
            g, half, stop_on_convergence=False))
        margin_fn = jax.jit(_belief_margin)
        rest = jax.jit(lambda g, s: cost_of(
            maxsum_ops.run_maxsum_from(
                g, s, half, stop_on_convergence=False)[1]))

        def run():
            state, values = first(placed)
            margin = np.asarray(margin_fn(placed, state))
            vals = np.asarray(jax.device_get(values))
            var_costs = np.asarray(
                jax.device_get(placed.var_costs)).copy()
            n_vars = var_costs.shape[0] - 1
            k = max(1, n_vars // 5)
            chosen = np.argsort(-margin, kind="stable")[:k]
            d = var_costs.shape[1]
            from pydcop_tpu.engine.compile import BIG

            for i in chosen:
                keep = int(vals[i])
                row = np.full((d,), BIG, var_costs.dtype)
                row[keep] = var_costs[i, keep]
                var_costs[i] = row
            g2 = placed._replace(
                var_costs=jax.device_put(var_costs))
            state = state._replace(stable=jnp.asarray(False))
            return float(rest(g2, state))

        return run

    def ls_runner(run_fn, **kw):
        fn = jax.jit(partial(run_fn, max_cycles=race_cycles, **kw))
        return lambda: float(fn(placed)[1])

    return {
        "maxsum": maxsum_runner(False),
        "maxsum_prune": maxsum_runner(True),
        "maxsum_decim": decim_runner(),
        "dsa": ls_runner(dsa_ops.run_dsa),
        "mgm": ls_runner(mgm_ops.run_mgm, lexic_ranks=ranks),
        "gdba": ls_runner(gdba_ops.run_gdba, lexic_ranks=ranks),
    }


def dpop_portfolio_runner(dcop, graph: CompiledFactorGraph, meta):
    """Zero-arg exact-inference race leg, or None past the width gate.

    Width is decided from the pseudo-tree BEFORE any table exists
    (ops/dpop.tree_stats via engine.dpop.dpop_feasibility, CEC
    shrinkage included), so an over-wide structure costs one cheap
    host-side pass and never allocates a hypercube.  The returned
    runner scores its assignment through the SAME compiled-graph
    ``assignment_cost`` the iterative racers use — one cost scale for
    the whole race (max-objective negation included)."""
    from pydcop_tpu.computations_graph import pseudotree as pt
    from pydcop_tpu.engine.dpop import DpopEngine, dpop_feasibility

    try:
        ptree = pt.build_computation_graph(dcop)
    except Exception as e:  # noqa: BLE001 — no tree, no exact leg
        logger.debug("portfolio: no pseudo-tree for dpop leg: %s", e)
        return None
    verdict = dpop_feasibility(
        ptree, mode=dcop.objective, cec=True,
        max_elements=DPOP_RACE_MAX_ELEMENTS)
    if not verdict["feasible"]:
        logger.debug(
            "portfolio: dpop leg skipped (max_elements %s > gate %s)",
            verdict["max_elements"], DPOP_RACE_MAX_ELEMENTS)
        return None
    import jax
    import jax.numpy as jnp

    from pydcop_tpu.ops.localsearch import assignment_cost

    engine = DpopEngine(ptree, mode=dcop.objective, cec=True)
    placed = jax.device_put(graph)
    index_of = {
        name: {v: i for i, v in enumerate(dom)}
        for name, dom in zip(meta.var_names, meta.domains)
    }

    def run():
        res = engine.run()
        idx = jnp.asarray(
            [index_of[n][res.assignment[n]] for n in meta.var_names]
            + [0], dtype=jnp.int32)
        return float(assignment_cost(placed, idx))

    return run


def _belief_margin(graph, state):
    import jax.numpy as jnp

    from pydcop_tpu.ops import maxsum as maxsum_ops

    beliefs, _ = maxsum_ops.aggregate_beliefs(graph, state.f2v)
    masked = jnp.where(graph.var_valid, beliefs, jnp.inf)[:-1]
    best2 = jnp.sort(masked, axis=1)[:, :2]
    return best2[:, 1] - best2[:, 0]


def autotune_portfolio(graph: CompiledFactorGraph, *,
                       key: Optional[str] = None,
                       race_cycles: int = _PORTFOLIO_RACE_CYCLES,
                       use_cache: bool = True,
                       cache_file: Optional[str] = None,
                       candidates=PORTFOLIO_CANDIDATES,
                       meta=None,
                       extra_runners=None,
                       ) -> Dict[str, Any]:
    """Race whole algorithm kernels on ``graph`` toward a cost target.

    Every candidate runs ``race_cycles`` cycles (warmed — compile
    excluded; honest sync through the host fetch of the scalar cost);
    the target cost is the best final cost any candidate achieved, and
    the winner is the fastest candidate within ``_PORTFOLIO_COST_TOL``
    of it — deterministic tie-break by candidate order (parity-default
    maxsum first).  A candidate that fails to build/run is dropped
    with a note, never fatal (maxsum always runs).

    Returns ``{"algo", "portfolio_source", "portfolio_timings_ms",
    "portfolio_costs", "portfolio_target_cost", "portfolio_key"}``;
    persists the decision under ``key`` in the shared JSON shape
    cache (``portfolio_source`` is ``"cache"`` on replay — asserted
    against re-racing in the work-reduction battery)."""
    import time as _time

    if key is None:
        key = portfolio_key(graph_shape_key(graph))
    path = cache_file or cache_path()
    if use_cache:
        cached = _load_cache(path).get(key)
        if isinstance(cached, dict) \
                and cached.get("algo") in PORTFOLIO_CANDIDATES:
            return {
                "algo": cached["algo"],
                "portfolio_source": "cache",
                "portfolio_timings_ms": cached.get(
                    "portfolio_timings_ms", {}),
                "portfolio_costs": cached.get("portfolio_costs", {}),
                "portfolio_target_cost": cached.get(
                    "portfolio_target_cost"),
                "portfolio_key": key,
            }

    runners = _portfolio_runners(graph, race_cycles, meta=meta)
    if extra_runners:
        # Conditional candidates (e.g. the width-gated dpop leg): a
        # None value means "not raced on this structure" — same as an
        # absent runner.
        runners.update(
            {k: v for k, v in extra_runners.items() if v is not None})
    timings_ms: Dict[str, Optional[float]] = {}
    costs: Dict[str, Optional[float]] = {}
    notes: Dict[str, str] = {}
    for name in candidates:
        runner = runners.get(name)
        if runner is None:
            continue
        try:
            runner()  # warm: compile + one discarded run
            t0 = _time.perf_counter()
            cost = runner()
            timings_ms[name] = round(
                (_time.perf_counter() - t0) * 1e3, 4)
            costs[name] = cost
        except Exception as e:  # noqa: BLE001 — drop the candidate
            notes[name] = f"{type(e).__name__}"
            logger.warning("portfolio: %s failed to race: %s",
                           name, e)
            timings_ms[name] = None
            costs[name] = None

    scored = {n: (costs[n], timings_ms[n]) for n in candidates
              if costs.get(n) is not None
              and timings_ms.get(n) is not None}
    if not scored:
        choice = "maxsum"
        target = None
    else:
        target = min(c for c, _ in scored.values())
        tol = abs(target) * _PORTFOLIO_COST_TOL + 1e-9
        eligible = {n: t for n, (c, t) in scored.items()
                    if c <= target + tol}
        order = {n: i for i, n in enumerate(candidates)}
        choice = min(eligible, key=lambda n: (eligible[n], order[n]))
    result = {
        "algo": choice,
        "portfolio_source": "measured",
        "portfolio_timings_ms": timings_ms,
        "portfolio_costs": costs,
        "portfolio_target_cost": target,
        "portfolio_key": key,
    }
    if notes:
        result["portfolio_notes"] = notes
    if use_cache:
        import jax

        _store_cache(path, {key: {
            "algo": choice,
            "portfolio_timings_ms": timings_ms,
            "portfolio_costs": costs,
            "portfolio_target_cost": target,
            "backend": jax.default_backend(),
        }})
    return result


# --------------------------------------------------------------------
# Self-tuning pack-planner constants (ISSUE 18 tentpole c)
#
# The envelope pack-vs-solo decision (serving/binning.pack_decision)
# prices dispatches with an affine model ``overhead + cycles *
# (per_cycle + cells * per_cell)`` whose constants were fitted ONCE on
# the CPU backend.  Every completed serving dispatch is a measured
# sample of exactly that model (the request ledger's execute wall, the
# dispatch's padded cell total, its cycle budget), so the constants
# are re-fitted online per resolved backend: an exponentially-weighted
# least-squares regression of ms-per-cycle on cells (intercept →
# us_per_cycle, slope → ns_per_cell_cycle) plus an EW mean of the
# per-dispatch host overhead.  Persisted in the same shape-cache JSON
# as the portfolio timings (key ``packfit-v1|<backend>``) so a restart
# starts from the fleet's history; cold start (< _PACKFIT_MIN_SAMPLES
# samples, or a degenerate fit) falls back to the compiled-in
# defaults.  ``PYDCOP_PACK_FIT=0`` disables both recording and use.

PACKFIT_PREFIX = f"packfit-v{_CACHE_VERSION}|"
_PACKFIT_DECAY = 0.98
_PACKFIT_MIN_SAMPLES = 8
_PACKFIT_PERSIST_EVERY = 16
_packfit_lock = threading.Lock()
# backend -> EW sufficient statistics {w, wx, wy, wxx, wxy, wo, n}
_packfit_state: Dict[str, Dict[str, float]] = {}
_packfit_dirty: Dict[str, int] = {}


def pack_fit_enabled() -> bool:
    """``PYDCOP_PACK_FIT=0`` freezes the pack planner on the
    compiled-in default constants (the on/off isolation knob the
    perf-smoke pairwise gate and the serving bench A/B use)."""
    return os.environ.get("PYDCOP_PACK_FIT", "1") != "0"


def _packfit_key(backend: str) -> str:
    return PACKFIT_PREFIX + str(backend)


def _packfit_load(backend: str,
                  cache_file: Optional[str] = None) -> Dict[str, float]:
    """Seed the in-memory EW state from the persisted JSON once per
    backend per process (under ``_packfit_lock``)."""
    state = _packfit_state.get(backend)
    if state is not None:
        return state
    persisted = _load_cache(cache_file or cache_path()).get(
        _packfit_key(backend))
    state = {"w": 0.0, "wx": 0.0, "wy": 0.0, "wxx": 0.0,
             "wxy": 0.0, "wo": 0.0, "n": 0.0}
    if isinstance(persisted, dict):
        stats = persisted.get("stats")
        if isinstance(stats, dict):
            for k in state:
                v = stats.get(k)
                if isinstance(v, (int, float)) and np.isfinite(v):
                    state[k] = float(v)
    _packfit_state[backend] = state
    return state


def record_pack_sample(backend: str, cells: int, cycles: int,
                       execute_s: float, overhead_s: float = 0.0,
                       cache_file: Optional[str] = None) -> None:
    """Feed one measured dispatch into the per-backend fit.

    ``execute_s`` is the dispatch's device execute wall (the ledger's
    ``execute`` component / the DeviceRunResult ``run_time_s`` of a
    warm dispatch), ``cells`` the PADDED cell total the device
    actually ran (``metrics['cells_total']``), ``overhead_s`` the
    host-side per-dispatch fixed cost (batch assembly + launch).
    Cold dispatches must not be fed — their wall is compile, not the
    affine compute model.  Persists every
    ``_PACKFIT_PERSIST_EVERY`` samples (atomic merge-write; failure
    degrades to in-memory-only)."""
    if not pack_fit_enabled():
        return
    if cells <= 0 or cycles <= 0 or execute_s <= 0:
        return
    x = float(cells)
    y = execute_s * 1e3 / float(cycles)  # ms per cycle
    with _packfit_lock:
        state = _packfit_load(backend, cache_file)
        d = _PACKFIT_DECAY
        for k in ("w", "wx", "wy", "wxx", "wxy", "wo"):
            state[k] *= d
        state["w"] += 1.0
        state["wx"] += x
        state["wy"] += y
        state["wxx"] += x * x
        state["wxy"] += x * y
        state["wo"] += max(overhead_s, 0.0) * 1e3
        state["n"] += 1.0
        _packfit_dirty[backend] = _packfit_dirty.get(backend, 0) + 1
        if _packfit_dirty[backend] >= _PACKFIT_PERSIST_EVERY:
            _packfit_dirty[backend] = 0
            fitted = _packfit_fit(state)
            _store_cache(cache_file or cache_path(), {
                _packfit_key(backend): {
                    "stats": dict(state),
                    "fitted": fitted,
                    "backend": backend,
                }})


def _packfit_fit(state: Dict[str, float]) -> Optional[Dict[str, float]]:
    """Solve the EW least squares for the model constants; None when
    under-sampled or degenerate (caller falls back to defaults)."""
    if state["n"] < _PACKFIT_MIN_SAMPLES or state["w"] <= 0:
        return None
    w, wx, wy, wxx, wxy = (state["w"], state["wx"], state["wy"],
                           state["wxx"], state["wxy"])
    denom = w * wxx - wx * wx
    if denom <= 1e-12:
        return None
    slope = (w * wxy - wx * wy) / denom        # ms/cycle per cell
    intercept = (wy - slope * wx) / w          # ms/cycle at 0 cells
    if not (np.isfinite(slope) and np.isfinite(intercept)):
        return None
    if slope <= 0 or intercept < 0:
        # A non-positive cell slope means the sampled range cannot
        # identify the model (e.g. one shape dominating traffic) —
        # an unidentified fit must not steer the planner.
        return None
    return {
        "us_per_cycle": round(intercept * 1e3, 6),
        "ns_per_cell_cycle": round(slope * 1e6, 6),
        "overhead_ms": round(state["wo"] / w, 6),
        "n": int(state["n"]),
    }


def fitted_pack_constants(backend: str,
                          cache_file: Optional[str] = None
                          ) -> Optional[Dict[str, float]]:
    """The current fitted constants for ``backend`` — the dict
    serving/binning.pack_decision consumes (``us_per_cycle``,
    ``ns_per_cell_cycle``, ``overhead_ms``, ``n``) — or None while
    cold/degenerate/disabled (the planner then uses the compiled-in
    defaults and records ``constants_source: "default"``)."""
    if not pack_fit_enabled():
        return None
    with _packfit_lock:
        state = _packfit_load(backend, cache_file)
        return _packfit_fit(state)


def _packfit_reset() -> None:
    """Test hook: drop the in-memory EW state (the JSON is untouched;
    point ``cache_file`` at a temp path to isolate persistence)."""
    with _packfit_lock:
        _packfit_state.clear()
        _packfit_dirty.clear()

"""Agent runtime: one thread per agent hosting N computations.

Reference parity: pydcop/infrastructure/agents.py (Agent :78 — thread
:140, add_computation :175, run/start :324, main loop _run :785-838,
clean_shutdown :431, metrics :717, set_periodic_action :743;
AgentMetrics :878; ResilientAgent :927).
"""

import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from pydcop_tpu.dcop.objects import AgentDef
from pydcop_tpu.infrastructure.communication import (
    CommunicationLayer,
    Messaging,
)
from pydcop_tpu.infrastructure.computations import (
    MessagePassingComputation,
)
from pydcop_tpu.infrastructure.discovery import Discovery


class AgentException(Exception):
    pass


class Agent:
    """A container running computations on its own thread.

    The agent pops messages from its Messaging priority queue, dispatches
    them to hosted computations, and runs registered periodic actions in
    between (reference loop: agents.py:785-838).
    """

    def __init__(self, name: str, comm: CommunicationLayer,
                 agent_def: Optional[AgentDef] = None,
                 delay: Optional[float] = None,
                 ui_port: Optional[int] = None):
        self._name = name
        self.agent_def = agent_def
        self._comm = comm
        self._messaging = Messaging(name, comm, delay=delay or 0)
        self.discovery = Discovery(name, comm.address)
        comm.discovery = self.discovery
        self.discovery.agent_change_hooks.append(comm.on_agent_change)
        self._computations: Dict[str, MessagePassingComputation] = {}
        self._thread = threading.Thread(
            target=self._run, name=f"agent_{name}", daemon=True
        )
        self._running = False
        self._stopping = threading.Event()
        self.logger = logging.getLogger(f"pydcop.agent.{name}")
        self._periodic: List[List] = []  # [period, action, next_due]
        self.t_active = 0.0
        self._start_time: Optional[float] = None
        # Orchestration hooks, set by OrchestratedAgent:
        self.on_value_change: Optional[Callable] = None
        self.on_cycle_change: Optional[Callable] = None
        self.on_computation_finished: Optional[Callable] = None
        self.add_computation(self.discovery.discovery_computation)
        # Optional live-observability websocket server (ui.py).
        self.ui_server = None
        if ui_port:
            from pydcop_tpu.infrastructure.ui import UiServer

            self.ui_server = UiServer(self, ui_port)
            self.ui_server.start()

    # -- properties ---------------------------------------------------- #

    @property
    def name(self) -> str:
        return self._name

    @property
    def address(self):
        return self._comm.address

    @property
    def messaging(self) -> Messaging:
        return self._messaging

    @property
    def is_running(self) -> bool:
        return self._running

    @property
    def computations(self) -> List[MessagePassingComputation]:
        return list(self._computations.values())

    def computation(self, name: str) -> MessagePassingComputation:
        try:
            return self._computations[name]
        except KeyError:
            raise AgentException(
                f"Agent {self.name} does not host computation {name}"
            )

    def has_computation(self, name: str) -> bool:
        return name in self._computations

    # -- computations -------------------------------------------------- #

    def add_computation(self, computation: MessagePassingComputation,
                        comp_name: Optional[str] = None):
        """Host a computation: wire its message sender to our queue,
        register it in messaging + discovery, and hook notifications
        (reference agents.py:175-221)."""
        name = comp_name or computation.name
        computation.message_sender = self._messaging.post_msg
        computation._periodic_action_handler = self._add_periodic
        computation._periodic_remove_handler = self.remove_periodic_action
        for period, _action, guarded in computation._periodic_actions:
            # Run the pause-guarded wrapper, not the raw action.
            self._add_periodic(period, guarded)
        self._computations[name] = computation
        self._messaging.register_computation(name)
        if not name.startswith("_"):
            self.discovery.register_computation(name, self._name)
        computation._on_value_cb = self._notify_value
        computation._on_cycle_cb = self._notify_cycle
        computation._on_finish_cb = self._notify_finished

    def remove_computation(self, name: str):
        comp = self._computations.pop(name, None)
        if comp is not None:
            comp.stop()
            # Drop its periodic wrappers from our schedule — otherwise
            # they keep firing for a computation we no longer host
            # (e.g. an ADSA tick after repair migrated it away).
            for _period, _action, guarded in comp._periodic_actions:
                self.remove_periodic_action(guarded)
            comp._periodic_action_handler = None
            comp._periodic_remove_handler = None
            self._messaging.unregister_computation(name)
            if not name.startswith("_"):
                self.discovery.unregister_computation(name)

    def _notify_value(self, comp):
        if self.on_value_change:
            self.on_value_change(comp)

    def _notify_cycle(self, comp):
        if self.on_cycle_change:
            self.on_cycle_change(comp)

    def _notify_finished(self, comp):
        if self.on_computation_finished:
            self.on_computation_finished(comp)

    # -- periodic actions ---------------------------------------------- #

    def _add_periodic(self, period: float, action: Callable):
        self._periodic.append([period, action, time.monotonic() + period])

    def set_periodic_action(self, period: float, action: Callable):
        """Run `action` every `period` seconds on the agent thread
        (reference agents.py:743)."""
        self._add_periodic(period, action)
        return action

    def remove_periodic_action(self, action):
        self._periodic = [p for p in self._periodic if p[1] is not action]

    # -- lifecycle ----------------------------------------------------- #

    def start(self):
        if self._running:
            raise AgentException(f"Agent {self.name} already started")
        self._running = True
        self._start_time = time.monotonic()
        self._thread.start()

    def run(self, computations: Optional[List[str]] = None):
        """Start hosted computations (all non-service ones by default)."""
        if computations is None:
            computations = [
                n for n in self._computations if not n.startswith("_")
            ]
        for name in computations:
            comp = self.computation(name)
            if not comp.is_running:
                comp.start()

    def _run(self):
        from pydcop_tpu.infrastructure import stats

        while not self._stopping.is_set():
            cmsg = self._messaging.next_msg(0.05)
            if cmsg is not None:
                t0 = time.monotonic()
                self._handle_message(cmsg)
                duration = time.monotonic() - t0
                self.t_active += duration
                if stats.tracing_enabled():
                    comp = self._computations.get(cmsg.dest_comp)
                    stats.trace_computation(
                        cmsg.dest_comp, duration,
                        msg_in_count=1, msg_in_size=cmsg.msg.size,
                        value=getattr(comp, "current_value", None),
                    )
            self._process_periodic()

    def _handle_message(self, cmsg):
        comp = self._computations.get(cmsg.dest_comp)
        if comp is None:
            self.logger.warning(
                "Message for unknown computation %s: %s",
                cmsg.dest_comp, cmsg.msg,
            )
            return
        try:
            comp.on_message(cmsg.src_comp, cmsg.msg, time.monotonic())
        except Exception:
            self.logger.exception(
                "Error handling message %s for %s", cmsg.msg, cmsg.dest_comp
            )

    def _process_periodic(self):
        now = time.monotonic()
        for entry in self._periodic:
            period, action, due = entry
            if now >= due:
                entry[2] = now + period
                try:
                    action()
                except Exception:
                    self.logger.exception("Error in periodic action")

    def stop(self):
        self._stopping.set()

    def clean_shutdown(self, timeout: float = 5):
        """Stop computations, drain, stop the thread and transport."""
        for comp in list(self._computations.values()):
            try:
                comp.stop()
            except Exception:
                self.logger.exception(
                    "Error stopping computation %s", comp.name
                )
        self.stop()
        self.join(timeout)
        if self.ui_server is not None:
            self.ui_server.stop()
        self._messaging.shutdown()

    def join(self, timeout: Optional[float] = None):
        if self._thread.is_alive():
            self._thread.join(timeout)

    # -- metrics ------------------------------------------------------- #

    def metrics(self) -> Dict:
        cycles = {}
        for name, comp in self._computations.items():
            if hasattr(comp, "cycle_count"):
                cycles[name] = comp.cycle_count
        return {
            "count_ext_msg": dict(self._messaging.count_ext_msg),
            "size_ext_msg": dict(self._messaging.size_ext_msg),
            "cycles": cycles,
            "activity_ratio": (
                self.t_active / (time.monotonic() - self._start_time)
                if self._start_time else 0
            ),
        }

    def __repr__(self):
        return f"Agent({self.name})"

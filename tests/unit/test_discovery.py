"""Discovery / Directory pub-sub tests (VERDICT weak #7: subscription
coverage — replica pub/sub, agent address-change events — was far
narrower than the reference's discovery.py:654-1397).

Wiring trick: the DirectoryComputation and per-agent
DiscoveryComputations are driven directly with an in-memory message
bus standing in for Messaging — no agents, no threads.
"""

from typing import Dict

from pydcop_tpu.infrastructure.discovery import (
    DIRECTORY_COMP,
    DirectoryComputation,
    Discovery,
    UnknownAgent,
)

import pytest


class Bus:
    """Synchronous message bus: post_msg(target, msg) dispatches to the
    registered computation immediately."""

    def __init__(self):
        self.comps: Dict[str, object] = {}

    def wire(self, comp):
        self.comps[comp.name] = comp

        def sender(src, target, msg, prio=0, on_error=None):
            self.comps[target].on_message(src, msg, 0)

        comp.message_sender = sender


@pytest.fixture()
def net():
    """A directory plus two agent-side discoveries on one bus."""
    bus = Bus()
    directory = DirectoryComputation()
    bus.wire(directory)

    def make_discovery(agent, address):
        disco = Discovery(agent, address)
        disco.use_directory("orchestrator", "orch_addr")
        comp = disco.discovery_computation
        bus.comps[comp.name] = comp
        # Route this discovery's outgoing messages over the bus with
        # the true sender name, so directory subscriptions record the
        # right subscriber computation.
        comp.message_sender = (
            lambda src, target, msg, prio=0, on_error=None:
            bus.comps[target].on_message(src, msg, 0)
        )
        return disco

    return bus, make_discovery


def test_agent_registration_publishes_to_subscriber(net):
    bus, make = net
    d1 = make("a1", "addr1")
    d2 = make("a2", "addr2")
    events = []
    d2.subscribe_agent("a1", lambda e, n, v: events.append((e, n, v)))
    d1.register_agent("a1", "addr1bis")
    assert d2.agent_address("a1") == "addr1bis"
    assert ("agent_added", "a1", "addr1bis") in events


def test_agent_address_change_fires_subscriber_again(net):
    """Address changes (agent re-registering on a new transport) must
    reach subscribers — the reference's agent address-change events."""
    bus, make = net
    d1 = make("a1", "addr1")
    d2 = make("a2", "addr2")
    events = []
    d2.subscribe_agent("a1", lambda e, n, v: events.append((e, n, v)))
    d1.register_agent("a1", ("host1", 9001))
    d1.register_agent("a1", ("host1", 9002))  # moved port
    assert d2.agent_address("a1") == ("host1", 9002)
    addresses = [v for e, n, v in events if e == "agent_added"]
    assert ("host1", 9001) in addresses and ("host1", 9002) in addresses


def test_agent_removal_publishes_and_clears_cache(net):
    bus, make = net
    d1 = make("a1", "addr1")
    d2 = make("a2", "addr2")
    events = []
    d2.subscribe_agent("a1", lambda e, n, v: events.append((e, n, v)))
    d1.register_agent("a1", "addr1")
    d1.unregister_agent("a1")
    assert ("agent_removed", "a1", None) in events
    with pytest.raises(UnknownAgent):
        d2.agent_address("a1")


def test_subscribe_syncs_current_state(net):
    """Subscribing to an already-registered name answers immediately
    with the current state (late subscriber sync)."""
    bus, make = net
    d1 = make("a1", "addr1")
    d1.register_agent("a1", "addr1")
    d1.register_computation("v1", "a1")
    d2 = make("a2", "addr2")
    d2.subscribe_computation("v1")
    assert d2.computation_agent("v1") == "a1"


def test_computation_pub_sub_and_unsubscribe(net):
    bus, make = net
    d1 = make("a1", "addr1")
    d2 = make("a2", "addr2")
    events = []
    d2.subscribe_computation(
        "v1", lambda e, n, v: events.append((e, n, v)))
    d1.register_computation("v1", "a1", address="addr1")
    assert d2.computation_agent("v1") == "a1"
    assert events and events[-1][0] == "computation_added"

    d2.unsubscribe_computation("v1")
    d1.unregister_computation("v1")
    # The unsubscribe removed the callback; cache no longer updated
    # via callback list (events unchanged).
    assert events[-1][0] == "computation_added"


def test_replica_pub_sub(net):
    """Replica registry: add/remove publications reach subscribers
    with the updated host list (reference discovery.py:1304,1397)."""
    bus, make = net
    d1 = make("a1", "addr1")
    d2 = make("a2", "addr2")
    events = []
    d2.subscribe_replica("v1", lambda e, n, v: events.append((e, n, v)))
    d1.register_replica("v1", "a3")
    d1.register_replica("v1", "a4")
    d1.unregister_replica("v1", "a3")
    assert d2.replica_agents("v1") == ["a4"]
    seq = [v for e, n, v in events if e == "replica_changed"]
    assert seq == [["a3"], ["a3", "a4"], ["a4"]]


def test_wildcard_subscription_sees_every_agent(net):
    bus, make = net
    d1 = make("a1", "addr1")
    d2 = make("a2", "addr2")
    events = []
    d2.subscribe_agent("*", lambda e, n, v: events.append((e, n, v)))
    d1.register_agent("a5", "addr5")
    d1.register_agent("a6", "addr6")
    # Wildcard publications arrive for names the subscriber never
    # named explicitly.
    names = {n for e, n, v in events if e == "agent_added"}
    assert {"a5", "a6"} <= names


def test_agent_change_hooks_fire_on_publications(net):
    """Transport purge hooks (HttpCommunicationLayer.on_agent_change)
    must fire for *published* removals, not just local ones."""
    bus, make = net
    d1 = make("a1", "addr1")
    d2 = make("a2", "addr2")
    hook_events = []
    d2.agent_change_hooks.append(
        lambda e, n: hook_events.append((e, n)))
    d2.subscribe_agent("a9")
    d1.register_agent("a9", "addr9")
    d1.unregister_agent("a9")
    assert ("agent_added", "a9") in hook_events
    assert ("agent_removed", "a9") in hook_events
"""Extended discovery battery on top of test_discovery.py's pub-sub
scenarios — local-cache semantics, standalone (no-directory) mode,
error paths, and multi-subscriber fan-out (reference
test_infra_discovery.py depth)."""

from typing import Dict

import pytest

from pydcop_tpu.infrastructure.discovery import (
    DIRECTORY_COMP,
    DirectoryComputation,
    Discovery,
    UnknownAgent,
)


class Bus:
    def __init__(self):
        self.comps: Dict[str, object] = {}

    def wire_comp(self, comp):
        self.comps[comp.name] = comp
        comp.message_sender = (
            lambda src, target, msg, prio=0, on_error=None:
            self.comps[target].on_message(src, msg, 0)
        )


@pytest.fixture()
def net():
    bus = Bus()
    directory = DirectoryComputation()
    bus.wire_comp(directory)

    def make(agent, address):
        disco = Discovery(agent, address)
        disco.use_directory("orchestrator", "orch_addr")
        bus.wire_comp(disco.discovery_computation)
        return disco

    return bus, make


class TestLocalCache:
    def test_own_agent_preseeded(self):
        d = Discovery("a1", "addr1")
        assert d.agent_address("a1") == "addr1"
        assert "a1" in d.agents()

    def test_unknown_agent_raises(self):
        d = Discovery("a1", "addr1")
        with pytest.raises(UnknownAgent):
            d.agent_address("ghost")

    def test_unknown_computation_raises_keyerror(self):
        d = Discovery("a1", "addr1")
        with pytest.raises(KeyError):
            d.computation_agent("ghost")

    def test_use_directory_seeds_cache(self):
        d = Discovery("a1", "addr1")
        d.use_directory("orch", "orch_addr")
        assert d.agent_address("orch") == "orch_addr"
        assert d.computation_agent(DIRECTORY_COMP) == "orch"

    def test_register_computation_defaults_to_own_agent(self):
        d = Discovery("a1", "addr1")
        d.register_computation("v1")
        assert d.computation_agent("v1") == "a1"

    def test_register_computation_with_address_caches_agent(self):
        d = Discovery("a1", "addr1")
        d.register_computation("v9", "a9", address="addr9")
        assert d.computation_agent("v9") == "a9"
        assert d.agent_address("a9") == "addr9"

    def test_unregister_computation_clears(self):
        d = Discovery("a1", "addr1")
        d.register_computation("v1")
        d.unregister_computation("v1")
        with pytest.raises(KeyError):
            d.computation_agent("v1")

    def test_replica_agents_default_empty(self):
        d = Discovery("a1", "addr1")
        assert d.replica_agents("v1") == []

    def test_standalone_mode_no_directory_is_local_only(self):
        # Without use_directory, registrations stay purely local and
        # never try to send anything (no directory to send to).
        d = Discovery("a1", "addr1")
        d.register_agent("a2", "addr2")
        d.register_computation("v1", "a2")
        d.unregister_agent("a2")
        with pytest.raises(UnknownAgent):
            d.agent_address("a2")


class TestHooks:
    def test_local_register_fires_hooks(self):
        d = Discovery("a1", "addr1")
        seen = []
        d.agent_change_hooks.append(lambda e, n: seen.append((e, n)))
        d.register_agent("a2", "x")
        d.unregister_agent("a2")
        assert seen == [("agent_added", "a2"), ("agent_removed", "a2")]

    def test_hook_exception_does_not_break_registration(self):
        d = Discovery("a1", "addr1")

        def bad_hook(e, n):
            raise RuntimeError("boom")

        d.agent_change_hooks.append(bad_hook)
        d.register_agent("a2", "x")   # must not raise
        assert d.agent_address("a2") == "x"


class TestFanOut:
    def test_multiple_subscribers_each_notified(self, net):
        bus, make = net
        d1 = make("a1", "addr1")
        d2 = make("a2", "addr2")
        d3 = make("a3", "addr3")
        ev2, ev3 = [], []
        d2.subscribe_agent("ax", lambda e, n, v: ev2.append((e, n)))
        d3.subscribe_agent("ax", lambda e, n, v: ev3.append((e, n)))
        d1.register_agent("ax", "addrx")
        assert ("agent_added", "ax") in ev2
        assert ("agent_added", "ax") in ev3

    def test_multiple_callbacks_same_subscriber(self, net):
        bus, make = net
        d1 = make("a1", "addr1")
        d2 = make("a2", "addr2")
        ev_a, ev_b = [], []
        d2.subscribe_agent("ax", lambda e, n, v: ev_a.append(e))
        d2.subscribe_agent("ax", lambda e, n, v: ev_b.append(e))
        d1.register_agent("ax", "addrx")
        assert ev_a == ["agent_added"] and ev_b == ["agent_added"]

    def test_non_subscriber_not_notified_or_synced(self, net):
        bus, make = net
        d1 = make("a1", "addr1")
        d2 = make("a2", "addr2")
        d1.register_agent("ax", "addrx")
        # d2 never subscribed to ax: its cache must not know it.
        with pytest.raises(UnknownAgent):
            d2.agent_address("ax")

    def test_computation_wildcard(self, net):
        bus, make = net
        d1 = make("a1", "addr1")
        d2 = make("a2", "addr2")
        names = []
        d2.subscribe_computation(
            "*", lambda e, n, v: names.append(n))
        d1.register_computation("c1", "a1", address="addr1")
        d1.register_computation("c2", "a1", address="addr1")
        assert {"c1", "c2"} <= set(names)

    def test_replica_late_subscriber_syncs_current_hosts(self, net):
        bus, make = net
        d1 = make("a1", "addr1")
        d1.register_replica("v1", "a7")
        d2 = make("a2", "addr2")
        d2.subscribe_replica("v1")
        assert d2.replica_agents("v1") == ["a7"]

    def test_unregister_replica_idempotent(self, net):
        bus, make = net
        d1 = make("a1", "addr1")
        d1.register_replica("v1", "a7")
        d1.unregister_replica("v1", "a7")
        d1.unregister_replica("v1", "a7")   # second removal: no error
        d2 = make("a2", "addr2")
        d2.subscribe_replica("v1")
        assert d2.replica_agents("v1") == []

"""DSA-tuto: the minimal tutorial DSA implementation.

Reference parity: pydcop/algorithms/dsatuto.py (:66-126) — DSA-A with
fixed probability 0.7, written as the companion of the algorithm
implementation tutorial (docs/tutorials/algo_implementation.rst).  The
device path delegates to the full dsa engine pinned to variant A.

Example (doctest, runs on the CPU backend under ``make doctest``)::

    >>> from pydcop_tpu.api import solve
    >>> from pydcop_tpu.dcop.dcop import DCOP
    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> from pydcop_tpu.dcop.relations import constraint_from_str
    >>> d = Domain('d', '', [0, 1])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> dcop = DCOP('doc', objective='min')
    >>> dcop.add_constraint(constraint_from_str('c', '(x + y - 1)**2', [x, y]))
    >>> res = solve(dcop, 'dsatuto', max_cycles=30, algo_params={'seed': 1})
    >>> round(res['cost'], 3)
    0.0
"""

from typing import Optional

from pydcop_tpu.algorithms import AlgoParameterDef, AlgorithmDef
from pydcop_tpu.algorithms import dsa as _dsa
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.engine.runner import DeviceRunResult

GRAPH_TYPE = "constraints_hypergraph"

algo_params = [
    AlgoParameterDef("variant", "str", ["A"], "A"),
    AlgoParameterDef("stop_cycle", "int", None, 0),
    AlgoParameterDef("seed", "int", None, 0),
]

computation_memory = _dsa.computation_memory
communication_load = _dsa.communication_load


def build_computation(comp_def):
    from pydcop_tpu.infrastructure.computations import build_algo_computation

    return build_algo_computation("dsatuto", comp_def)


def solve_on_device(dcop: DCOP, algo_def: AlgorithmDef,
                    max_cycles: int = 1000, mesh=None,
                    n_devices: Optional[int] = None,
                    warmup: bool = False,
                    **_) -> DeviceRunResult:
    inner = AlgorithmDef(
        "dsa",
        {
            "probability": 0.7,
            "p_mode": "fixed",
            "variant": "A",
            "stop_cycle": algo_def.params.get("stop_cycle", 0),
            "seed": algo_def.params.get("seed", 0),
        },
        algo_def.mode,
    )
    return _dsa.solve_on_device(
        dcop, inner, max_cycles=max_cycles, mesh=mesh,
        n_devices=n_devices, warmup=warmup,
    )

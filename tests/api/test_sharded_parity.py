"""Sharded-vs-unsharded parity for the WHOLE device algorithm family.

Round-4 verdict: sharded parity was asserted for 2 of 14 algorithms;
"the mesh is just bigger" was a claim, not a test, for the other 12.
This battery runs every algorithm with a device path through
``api.solve`` twice — single device and sharded over the 8-virtual-
device mesh (``n_devices=8``) — and asserts the results agree.

Reference analogue: the distribution layer works for every algorithm
(pydcop/distribution/objects.py:36 Distribution is algorithm-
agnostic); the sharding replacement must be too.

Parity tiers, by numeric class (docs/performance.md "Sharded
all-reduce" + __graft_entry__.dryrun_multichip rationale):

- **integer-cost local search** (dsa, dsatuto, adsa, mgm, mgm2, dba,
  gdba, mixeddsa): f32 sums of integer costs are exact, so the
  sharded trajectory is BIT-identical — identical assignment, cost,
  and cycle count at any cycle budget, even on loopy graphs;
- **maxsum family** (maxsum, amaxsum, maxsum_dynamic): float messages
  — the mesh all-reduce reassociates sums, so exact cross-topology
  parity is asserted on a QUIESCENT (tree) instance where
  send-suppression freezes the fixpoint;
- **exact solvers** (dpop, syncbb, ncbb): the mesh changes row padding
  (dpop) or is accepted-and-unused (host-driven B&B) — optimal cost
  must be identical either way.
"""

import numpy as np
import pytest

import jax

from pydcop_tpu.api import solve
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
)

N_DEVICES = 8


def _loopy_int_dcop(n_vars=24, n_edges=36, d=3, seed=0):
    """Random loopy binary DCOP with integer tables (exact f32 sums)."""
    rng = np.random.default_rng(seed)
    dom = Domain("d", "", list(range(d)))
    dcop = DCOP("loopy", objective="min")
    variables = [Variable(f"v{i}", dom) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    seen = set()
    k = 0
    while k < n_edges:
        i, j = rng.choice(n_vars, size=2, replace=False)
        key = (min(i, j), max(i, j))
        if key in seen:
            continue
        seen.add(key)
        table = rng.integers(0, 10, size=(d, d)).astype(np.float64)
        dcop.add_constraint(NAryMatrixRelation(
            [variables[i], variables[j]], table, f"c{k}"))
        k += 1
    return dcop


def _tree_dcop(n_vars=24, d=3, seed=1):
    """Random tree: MaxSum quiesces (every edge send-suppressed), so
    sharded and single-device runs reach the identical fixpoint."""
    rng = np.random.default_rng(seed)
    dom = Domain("d", "", list(range(d)))
    dcop = DCOP("tree", objective="min")
    variables = [Variable(f"v{i}", dom) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    for i in range(1, n_vars):
        parent = int(rng.integers(0, i))
        table = rng.integers(0, 10, size=(d, d)).astype(np.float64)
        dcop.add_constraint(NAryMatrixRelation(
            [variables[parent], variables[i]], table, f"c{i}"))
    return dcop


def _small_dcop(n_vars=8, n_cons=12, d=3, seed=2):
    return _loopy_int_dcop(n_vars=n_vars, n_edges=n_cons, d=d,
                           seed=seed)


def _pair(dcop, algo, max_cycles=30, algo_params=None):
    single = solve(dcop, algo, backend="device", max_cycles=max_cycles,
                   algo_params=algo_params)
    sharded = solve(dcop, algo, backend="device",
                    max_cycles=max_cycles, n_devices=N_DEVICES,
                    algo_params=algo_params)
    return single, sharded


LOCAL_SEARCH = [
    ("dsa", {"seed": 3}),
    ("dsatuto", {"seed": 3}),
    ("adsa", {"seed": 3, "stop_cycle": 30}),
    ("mgm", {"seed": 3}),
    ("mgm2", {"seed": 3}),
    ("dba", {"seed": 3}),
    ("gdba", {"seed": 3}),
    ("mixeddsa", {"seed": 3}),
]


@pytest.mark.parametrize(
    "algo,params", LOCAL_SEARCH, ids=[a for a, _ in LOCAL_SEARCH])
def test_local_search_bit_parity(algo, params):
    dcop = _loopy_int_dcop()
    single, sharded = _pair(dcop, algo, algo_params=params)
    assert sharded.assignment == single.assignment, (
        f"{algo}: sharded assignment diverged")
    assert sharded.cost == single.cost


@pytest.mark.parametrize("algo", ["maxsum", "amaxsum", "maxsum_dynamic"])
def test_maxsum_family_fixpoint_parity(algo):
    dcop = _tree_dcop()
    single, sharded = _pair(dcop, algo, max_cycles=200)
    assert sharded.assignment == single.assignment, (
        f"{algo}: sharded fixpoint diverged on a quiescent problem")
    assert sharded.cost == single.cost


@pytest.mark.parametrize("algo", ["dpop", "syncbb", "ncbb"])
def test_exact_solvers_cost_parity(algo):
    dcop = _small_dcop()
    single, sharded = _pair(dcop, algo)
    assert sharded.cost == pytest.approx(single.cost)
    assert sharded.assignment == single.assignment


# ------------------------------------------------------------------ #
# Partitioned engine (ISSUE 7): shards= runs the min-edge-cut /
# halo-exchange path, a different kernel from the replicated
# n_devices= mesh — parity is asserted separately, across the full
# 1/2/8 forced-host-device ladder, including a mid-solve
# checkpointed resume.


def _grid_dcop(side=10, seed=4):
    """4-neighbor grid coloring: the locally-connected loopy shape
    the partitioner is built for (single-digit-percent cuts).  One
    shared builder across the bench, the shard-smoke gate and both
    test batteries — see bench.build_grid_dcop."""
    from bench import build_grid_dcop

    return build_grid_dcop(side, seed=seed)


@pytest.mark.parametrize("shards", [2, 8])
@pytest.mark.parametrize("topo", ["grid", "loopy", "tree"])
def test_partitioned_assignment_parity(topo, shards):
    """Partitioned maxsum across the device ladder: identical
    assignment and cost to the single-device engine on grids (the
    partitioner's home turf), expander-like loopy graphs (worst-case
    cuts) and trees (quiescent fixpoint)."""
    dcop = {"grid": _grid_dcop, "loopy": _loopy_int_dcop,
            "tree": _tree_dcop}[topo]()
    single = solve(dcop, "maxsum", backend="device", max_cycles=60)
    sharded = solve(dcop, "maxsum", backend="device", max_cycles=60,
                    shards=shards)
    assert sharded.assignment == single.assignment, (
        f"partitioned maxsum diverged on {topo} at {shards} shards")
    assert sharded.cost == single.cost
    m = sharded["metrics"]
    assert m["n_shards"] == shards
    assert 0.0 <= m["edge_cut_fraction"] <= 1.0
    assert len(m["halo_vars_per_shard"]) == shards
    # O(cut*D) < O(V*D): the whole point of the partitioned path.
    assert (m["halo_exchange_elems_per_superstep"]
            < m["replicated_allreduce_elems_per_superstep"])


def test_partitioned_cost_trajectory_parity():
    """Per-cycle cost traces agree across 1/2/8 devices: the
    partitioned per-shard cost psum is a partition of the global sum
    (each factor and variable owned exactly once)."""
    from pydcop_tpu.algorithms.maxsum import build_engine

    dcop = _grid_dcop()
    params = {"noise": 0.01}
    ref = build_engine(dcop, params).run_trace(max_cycles=40)
    for shards in (2, 8):
        trace = build_engine(
            dcop, params, shards=shards).run_trace(max_cycles=40)
        np.testing.assert_allclose(
            trace.metrics["cost_trace"], ref.metrics["cost_trace"],
            rtol=1e-5,
            err_msg=f"cost trajectory diverged at {shards} shards")


@pytest.mark.parametrize("algo,params", [
    ("maxsum", {}),
    ("dsa", {"seed": 3}),
    ("mgm", {"seed": 3}),
])
@pytest.mark.parametrize("n", [2, 8])
def test_device_ladder_parity(algo, params, n):
    """The ISSUE-7 ladder: maxsum rides the partitioned engine
    (shards=), the local-search kernels ride the replicated mesh
    (n_devices=) — each across 1/2/8 forced host devices with
    identical assignments and costs."""
    dcop = _grid_dcop()
    single = solve(dcop, algo, backend="device", max_cycles=30,
                   algo_params=params)
    kwargs = ({"shards": n} if algo == "maxsum"
              else {"n_devices": n})
    sharded = solve(dcop, algo, backend="device", max_cycles=30,
                    algo_params=params, **kwargs)
    assert sharded.assignment == single.assignment
    assert sharded.cost == single.cost


def test_partitioned_checkpoint_resume_mid_solve(tmp_path):
    """run_checkpointed on a sharded graph, interrupted mid-solve and
    resumed: the resumed trajectory equals the uninterrupted one
    (assignment, cost, cycle count) — the halo double-buffer is part
    of the snapshot, so a resume re-enters the exchange exactly where
    it left off."""
    from pydcop_tpu.algorithms.maxsum import build_engine
    from pydcop_tpu.resilience.checkpoint import resume_from_checkpoint

    dcop = _grid_dcop()
    params = {"noise": 0.01}
    ref = build_engine(dcop, params, shards=8).run_checkpointed(
        max_cycles=60, segment_cycles=20, stop_on_convergence=False)

    interrupted = build_engine(
        dcop, params, shards=8).run_checkpointed(
        max_cycles=60, segment_cycles=20, stop_on_convergence=False,
        checkpoint_dir=str(tmp_path), max_segments=2)
    assert interrupted.metrics["interrupted"]
    assert interrupted.cycles == 40

    resumed = resume_from_checkpoint(
        build_engine(dcop, params, shards=8), str(tmp_path),
        max_cycles=60, stop_on_convergence=False)
    assert resumed.metrics["resumed_from_cycle"] == 40
    assert resumed.cycles == ref.cycles
    assert resumed.assignment == ref.assignment


def test_all_fourteen_covered():
    """The battery must cover every algorithm exposing a device path
    (pkgutil discovery — a 15th algorithm without a parity row fails
    here, keeping this file honest as the family grows)."""
    from pydcop_tpu.algorithms import list_available_algorithms

    covered = {a for a, _ in LOCAL_SEARCH} | {
        "maxsum", "amaxsum", "maxsum_dynamic", "dpop", "syncbb", "ncbb",
    }
    available = set(list_available_algorithms())
    missing = available - covered
    assert not missing, (
        f"algorithms without a sharded-parity row: {sorted(missing)}")

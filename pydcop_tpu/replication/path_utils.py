"""Path-table algebra for the UCS replica-placement search.

Reference parity: pydcop/replication/path_utils.py (cheapest_path_to
:99, affordable_path_from :125, filter_missing_agents_paths :135,
head/last/before_last :38-78).

A *path* is a tuple of agent names from the replication origin to a
candidate host; a *paths table* is a list of ``(cost, path)`` entries
kept sorted by cost (cheapest first).  All functions are pure — they
return new tables instead of mutating, which keeps the search state
easy to reason about (and to snapshot into messages).
"""

import bisect
from typing import Iterable, List, Optional, Sequence, Tuple

Path = Tuple[str, ...]
PathsTable = List[Tuple[float, Path]]


def head(path: Sequence[str]) -> Optional[str]:
    """First node of a path (origin agent)."""
    return path[0] if path else None


def last(path: Sequence[str]) -> Optional[str]:
    """Last node of a path (the candidate host)."""
    return path[-1] if path else None


def before_last(path: Sequence[str]) -> Optional[str]:
    """The node just before the last one."""
    if len(path) < 2:
        raise IndexError(f"Path {path} has no before-last element")
    return path[-2]


def add_path(paths: PathsTable, cost: float, path: Path) -> PathsTable:
    """Return a new table with (cost, path) inserted in sorted order."""
    new = list(paths)
    bisect.insort(new, (cost, path))
    return new


def remove_path(paths: PathsTable, path: Path) -> PathsTable:
    """Return a new table without any entry for `path`."""
    return [(c, p) for c, p in paths if p != path]


def cheapest_path_to(target: str, paths: PathsTable
                     ) -> Tuple[float, Path]:
    """Cheapest path ending at `target`; (inf, ()) if none."""
    for cost, path in paths:
        if last(path) == target:
            return cost, path
    return float("inf"), ()


def affordable_path_from(prefix: Path, max_cost: float,
                         paths: PathsTable) -> PathsTable:
    """All paths extending `prefix` whose cost is <= max_cost."""
    n = len(prefix)
    return [
        (cost, path) for cost, path in paths
        if cost <= max_cost and path[:n] == prefix and len(path) > n
    ]


def filter_missing_agents_paths(paths: PathsTable,
                                available: Iterable[str]) -> PathsTable:
    """Drop paths that traverse an agent that has left the system."""
    available = set(available)
    return [
        (cost, path) for cost, path in paths
        if all(node in available for node in path[1:])
    ]

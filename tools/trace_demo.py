"""Trace-demo gate: solve a small graph-coloring instance with
``--trace`` + ``--metrics`` through the real CLI and assert the
artifacts validate — the Chrome trace loads as JSON with well-nested
spans and the expected span kinds, the metrics JSONL parses with a
monotone cycle counter, the Prometheus dump is well-formed, and
``pydcop trace summary`` aggregates the file without error.  A live
telemetry leg then starts the HTTP endpoint on port 0, scrapes
``/metrics`` twice MID-RUN around an advancing segmented solve, and
asserts both scrapes parse with a strictly increasing
``pydcop_cycles_total`` (plus ``/healthz`` answering 200).

Run: ``make trace-demo`` (part of ``make test``).  Exit 0 = clean.
"""

import json
import os
import re
import sys
import tempfile
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

DCOP_YAML = """\
name: trace_demo
objective: min
domains:
  colors:
    values: [R, G, B]
variables:
  v0: {domain: colors}
  v1: {domain: colors}
  v2: {domain: colors}
  v3: {domain: colors}
constraints:
  c0:
    type: intention
    function: 10 if v0 == v1 else 0
  c1:
    type: intention
    function: 10 if v1 == v2 else 0
  c2:
    type: intention
    function: 10 if v2 == v3 else 0
  c3:
    type: intention
    function: 10 if v3 == v0 else 0
agents: [a0, a1, a2, a3]
"""

_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? (-?[0-9.e+-]+|\+Inf)"
    # Optional OpenMetrics exemplar suffix on bucket samples
    # (`# {trace_id="..."} value ts`) — present once anything
    # observed a histogram with an exemplar.
    r"( # \{[^}]*\} -?[0-9.e+-]+( [0-9.]+)?)?$"
)


def fail(message: str) -> int:
    print(f"trace_demo: FAIL: {message}")
    return 1


def main() -> int:
    from pydcop_tpu.dcop_cli import main as cli_main
    from pydcop_tpu.observability.trace import (
        check_well_nested,
        load_trace_file,
    )

    with tempfile.TemporaryDirectory(prefix="trace_demo_") as tmp:
        dcop_file = os.path.join(tmp, "coloring.yaml")
        with open(dcop_file, "w", encoding="utf-8") as f:
            f.write(DCOP_YAML)
        trace_file = os.path.join(tmp, "trace.json")
        metrics_file = os.path.join(tmp, "metrics.jsonl")
        out_file = os.path.join(tmp, "result.json")

        rc = cli_main([
            "--output", out_file,
            "solve", "-a", "maxsum", "-c", "60",
            "--trace", trace_file, "--metrics", metrics_file,
            "--metrics_every", "10", dcop_file,
        ])
        if rc != 0:
            return fail(f"pydcop solve exited {rc}")
        result = json.load(open(out_file, encoding="utf-8"))
        if result.get("violation") != 0:
            return fail(f"demo solve left violations: {result}")

        # 1. Chrome trace: json loads, spans well-nested, the engine
        # span kinds present.
        events = load_trace_file(trace_file)
        if not events:
            return fail("trace file has no events")
        try:
            check_well_nested(events)
        except ValueError as e:
            return fail(f"trace spans not well nested: {e}")
        names = {ev.get("name") for ev in events}
        missing = {"solve", "engine_segment", "chunk"} - names
        if missing:
            return fail(f"trace missing span kinds: {sorted(missing)}")

        # 2. Metrics JSONL: parses, monotone cycle counter.
        rows = [json.loads(line)
                for line in open(metrics_file, encoding="utf-8")]
        if not rows:
            return fail("metrics file has no snapshots")
        cycles = [row["cycle"] for row in rows]
        if cycles != sorted(cycles) or cycles[-1] <= 0:
            return fail(f"cycle counter not monotone: {cycles}")

        # 3. Prometheus dump: HELP/TYPE lines + parsable samples.
        prom = open(f"{metrics_file}.prom", encoding="utf-8").read()
        if "# HELP pydcop_cycles_total" not in prom or \
                "# TYPE pydcop_cycles_total counter" not in prom:
            return fail("prometheus dump missing cycle counter family")
        for line in prom.strip().splitlines():
            if not line.startswith("#") and not _PROM_SAMPLE.match(line):
                return fail(f"unparsable prometheus sample: {line!r}")

        # 4. The summary command aggregates the trace without error —
        # in both human and machine form.
        rc = cli_main(["trace", "summary", trace_file])
        if rc != 0:
            return fail(f"pydcop trace summary exited {rc}")
        rc = cli_main(["trace", "summary", "--json", trace_file])
        if rc != 0:
            return fail(f"pydcop trace summary --json exited {rc}")

        # 5. Live telemetry endpoint, scraped MID-RUN.
        err = check_live_endpoint(dcop_file)
        if err:
            return fail(err)

        # 6. Request-scoped tracing (ISSUE 9): a served burst leaves
        # every request reconstructable by `pydcop trace query`.
        err = check_request_tracing(os.path.join(tmp, "serve.jsonl"))
        if err:
            return fail(err)

    print("trace_demo: OK (trace + metrics + summary + live "
          "endpoint + request query all validate)")
    return 0


def _scrape(url: str) -> str:
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.read().decode("utf-8")


def _parse_prom(text: str, what: str):
    """Validate Prometheus text; return the parsed samples dict or an
    error string."""
    samples = {}
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        if not _PROM_SAMPLE.match(line):
            return None, f"{what}: unparsable sample: {line!r}"
        name_part, value = line.rsplit(" ", 1)
        samples[name_part] = float(value)
    return samples, None


def check_live_endpoint(dcop_file: str):
    """Start the telemetry server on port 0, advance a segmented
    engine solve on a background thread, scrape /metrics twice while
    it runs and assert the cycle counter moved.  Returns an error
    string or None."""
    from pydcop_tpu.dcop.yamldcop import load_dcop_from_file
    from pydcop_tpu.engine.compile import compile_dcop
    from pydcop_tpu.engine.runner import MaxSumEngine
    from pydcop_tpu.observability.engine_probe import EngineProbe
    from pydcop_tpu.observability.metrics import registry
    from pydcop_tpu.observability.server import TelemetryServer

    dcop = load_dcop_from_file([dcop_file])
    graph, meta = compile_dcop(dcop, noise_level=0.01)
    engine = MaxSumEngine(graph, meta)
    probe = EngineProbe(engine)
    server = TelemetryServer(port=0).start()
    url = server.url
    done = threading.Event()

    def run():
        try:
            # Tiny segments keep the host boundary (where the
            # snapshotter fires) hot; no convergence stop so the run
            # outlives both scrapes.  2500 cycles ≈ a second or two:
            # long enough that the scrapes land mid-run, short enough
            # that the success path's drain wait below stays cheap.
            engine.run_checkpointed(
                max_cycles=2_500, segment_cycles=5,
                stop_on_convergence=False, probe=probe)
        finally:
            done.set()

    thread = threading.Thread(target=run, daemon=True)
    try:
        before = registry.value("pydcop_cycles_total")
        thread.start()
        first, err = _parse_prom(_scrape(f"{url}/metrics"),
                                 "live /metrics scrape 1")
        if err:
            return err
        # Wait (bounded) for the counter to advance, then rescrape:
        # the increase must be visible THROUGH the endpoint.
        deadline = time.time() + 30
        second = None
        while time.time() < deadline and not done.is_set():
            text = _scrape(f"{url}/metrics")
            second, err = _parse_prom(text, "live /metrics scrape 2")
            if err:
                return err
            if second.get("pydcop_cycles_total", 0) > max(
                    first.get("pydcop_cycles_total", 0), before):
                break
            time.sleep(0.05)
        c1 = first.get("pydcop_cycles_total", 0)
        c2 = (second or {}).get("pydcop_cycles_total", 0)
        if not (second and c2 > c1):
            return (f"cycle counter did not increase between live "
                    f"scrapes ({c1} -> {c2})")
        health = json.loads(_scrape(f"{url}/healthz"))
        if health.get("status") != "ok":
            return f"unexpected /healthz verdict: {health}"
    finally:
        done.wait(60)
        server.stop()
    return None


def check_request_tracing(trace_path: str):
    """ISSUE 9 gate: serve a 3-request burst with tracing on, then
    `pydcop trace query --request ID` (the real CLI, on the exported
    trace) must reconstruct ONE well-nested tree whose spans cover
    submit → queue → dispatch → engine, all tagged with that
    request's trace_id.  Returns an error string or None."""
    import contextlib
    import io

    import numpy as np

    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation
    from pydcop_tpu.dcop_cli import main as cli_main
    from pydcop_tpu.observability.trace import tracer
    from pydcop_tpu.serving.service import SolveService

    def instance(seed):
        rng = np.random.default_rng(seed)
        dom = Domain("c", "", [0, 1, 2])
        dcop = DCOP(f"demo{seed}", objective="min")
        vs = [Variable(f"v{i}", dom) for i in range(6)]
        for v in vs:
            dcop.add_variable(v)
        for k in range(6):
            dcop.add_constraint(NAryMatrixRelation(
                [vs[k], vs[(k + 1) % 6]],
                rng.integers(0, 10, size=(3, 3)).astype(float),
                f"c{k}"))
        dcop.add_agents([AgentDef("a0")])
        return dcop

    tracer.enable()
    svc = SolveService(batch_window_s=0.2, max_batch=4)
    svc.start()
    try:
        rids = [svc.submit(instance(100 + i),
                           params={"max_cycles": 40})
                for i in range(3)]
        trace_ids = []
        for rid in rids:
            result = svc.result(rid, wait=60.0)
            if result is None or result["status"] != "FINISHED":
                return f"burst request {rid} did not finish: {result}"
            trace_ids.append(result["trace_id"])
        if len(set(trace_ids)) != 3:
            return f"trace_ids not distinct: {trace_ids}"
    finally:
        svc.stop(drain=False)
        tracer.export_jsonl(trace_path)
        tracer.disable()

    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = cli_main(["trace", "query", "--request", trace_ids[0],
                       "--json", trace_path])
    if rc != 0:
        return f"pydcop trace query exited {rc}"
    tree = json.loads(out.getvalue())
    if not tree["well_nested"]:
        return "queried request tree is not well-nested"
    names = set(tree["names"])
    needed = {"serve_submit", "serve_queued", "serve_dispatch",
              "engine_segment"}
    if not needed <= names:
        return (f"request tree missing spans: "
                f"{sorted(needed - names)} (have {sorted(names)})")

    def flat(nodes):
        for node in nodes:
            yield node
            yield from flat(node["children"])

    for node in flat(tree["tree"]):
        args = node["args"]
        if not (args.get("trace_id") == trace_ids[0]
                or trace_ids[0] in (args.get("trace_ids") or [])):
            return (f"{node['name']} span not tagged with the "
                    "request's trace_id")
    return None


if __name__ == "__main__":
    sys.exit(main())

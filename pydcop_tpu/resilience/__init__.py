"""Resilience subsystem: faults, checkpoints, retry, self-healing.

The reference ships resilience as a first-class capability
(ResilientAgent, computation replication, distribution reparation);
this package adds the pieces that *exercise* and *harden* that stack:

- :mod:`pydcop_tpu.resilience.faults` — deterministic, seed-driven
  fault injection (message drop / duplicate / delay / partition with
  optional healing, agent crash schedules) over any
  ``CommunicationLayer``;
- :mod:`pydcop_tpu.resilience.checkpoint` — checksummed NPZ snapshots
  of device-resident solver state plus ``resume_from_checkpoint``
  that falls back to the newest *valid* snapshot on corruption;
- :mod:`pydcop_tpu.resilience.retry` — ``RetryPolicy`` (exponential
  backoff + jitter + deadline) and ``CircuitBreaker``, applied to the
  HTTP transport, remote messaging and the multihost coordinator join;
- :mod:`pydcop_tpu.resilience.health` — active failure detection:
  per-agent heartbeat emitters and a phi-accrual ``HealthMonitor``
  whose bounded death verdicts feed the replication/reparation path;
- :mod:`pydcop_tpu.resilience.recovery` — guarded engine segments:
  ``RecoveryPolicy`` rolls a tripped solve (NaN/Inf, cost divergence)
  back to the last valid snapshot and re-runs with escalating
  intervention, bounded by a restart budget.

See docs/resilience.md for knobs and the agent-repair flow;
``tools/chaos_soak.py`` (``make chaos-soak``) is the invariant-
asserting scenario matrix over all of it.
"""

from pydcop_tpu.resilience.retry import (  # noqa: F401
    CircuitBreaker,
    CircuitOpenError,
    RetryExhaustedError,
    RetryPolicy,
)

"""Stateful solve sessions: incremental dynamic-DCOP serving.

A one-shot ``POST /solve`` answers one problem and forgets it.  A
*session* is a solve that LIVES across requests — the workload shape
of the reference's ``Scenario`` model (sensor nets, meeting
scheduling, smart grids: events mutate the problem mid-run) and of
every long-lived production client (ROADMAP open item 1):

- ``POST /session`` opens a solve backed by ONE
  :class:`~pydcop_tpu.engine.dynamic.DynamicMaxSumEngine`, owned by
  the scheduler thread (the same single thread that owns every other
  device dispatch);
- ``PATCH /session/<id>/events`` streams scenario events
  (change/add/remove factor, add variable, agent placement — the
  ``dcop/scenario.py`` vocabulary, engine/dynamic.apply_action)
  that are applied BETWEEN engine segments.  In-shape edits are pure
  array surgery — zero recompiles, the structure-cache hit; the
  engine re-keys only when the shape envelope dies (slack exhausted,
  new variable).  Messages warm-start from the pre-event fixpoint and
  decimation clamps release on the TOUCHED variables only;
- ``GET /session/<id>/events`` (SSE) streams anytime
  assignment/cost after every segment;
- ``DELETE /session/<id>`` closes the session with a final result.

Durability rides the PR-8 journal (serving/journal.py): the open, every
acknowledged event batch, periodic engine-state checkpoints and the
close are all records, so ``--recover`` replays WHOLE sessions after a
SIGKILL — rebuild the engine from the open record, re-apply the
pre-checkpoint batches structurally, restore the checkpointed message
state, apply the journaled-but-unapplied batches, and re-converge warm
(:meth:`SessionManager.recover`).  A PATCH's 200 is the same durable
promise a submit's 202 is: the record reaches the OS before the ack.

Wire protocol, recovery semantics and knobs: docs/sessions.md.
"""

import contextlib
import logging
import os
import queue
import tempfile
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from pydcop_tpu.engine.dynamic import (
    EVENT_ACTIONS,
    apply_action,
    build_dynamic_engine,
)
from pydcop_tpu.observability import flight
from pydcop_tpu.observability.metrics import CycleSnapshotter
from pydcop_tpu.observability.metrics import registry as metrics_registry
from pydcop_tpu.observability.trace import tracer
from pydcop_tpu.serving import journal as journal_mod
from pydcop_tpu.serving.admission import AdmissionRejected

logger = logging.getLogger("pydcop.serving.sessions")

# Session states.  OPEN sessions accept events and run segments;
# CLOSED/ERROR are terminal; REPLAYABLE is terminal for THIS process
# only — the journal still holds the session, a --recover restart
# resumes it.  MIGRATING freezes new event acks while a migration
# export drains the session (serving/migration.py) — it resolves to
# MIGRATED (terminal here: another replica owns the warm engine now)
# or back to OPEN when the move fails.
OPEN = "OPEN"
CLOSED = "CLOSED"
ERROR = "ERROR"
REPLAYABLE = "REPLAYABLE"
MIGRATING = "MIGRATING"
MIGRATED = "MIGRATED"
# Epoch fencing (partition tolerance): a replica that kept serving a
# session through a partition while the router repointed ownership
# elsewhere holds a STALE copy — when the partition heals, the copy
# is FENCED (terminal here: every write 409s) instead of silently
# double-applying events the new owner already owns.
FENCED = "FENCED"

# checkpoint_session sentinel: "compute the rebased problem yourself"
# vs. an explicit rebased yaml (or None for a plain marker) the
# export path already computed.
_UNSET = object()

# Session solver parameters and their defaults.  ``max_cycles`` is the
# re-convergence budget per ACTIVATION (open, or one event batch);
# ``segment_cycles`` the anytime-stream granularity — smaller segments
# mean fresher SSE assignments at more host syncs.  ``slack`` is the
# engine's spare-factor-row fraction (the in-place-mutation budget:
# bigger slack = more add_factor events before a recompile).
# ``decimation_margin`` (None = off) clamps decided variables between
# segments; events release clamps on touched variables only.
SESSION_PARAMS: Dict[str, Any] = {
    "max_cycles": 500,
    "segment_cycles": 50,
    "damping": 0.5,
    "damping_nodes": "both",
    "stability": 0.1,
    "noise": 0.01,
    "slack": 0.25,
    "decimation_margin": None,
}

_DAMPING_NODES = ("vars", "factors", "both", "none")


def normalize_session_params(
        overrides: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Session-parameter canonicalization, same contract as
    serving/binning.normalize_params: unknown keys and untypeable
    values raise (400 at the front end), never reach the scheduler
    thread."""
    params = dict(SESSION_PARAMS)
    for key, value in (overrides or {}).items():
        if key not in SESSION_PARAMS:
            raise ValueError(
                f"unknown session parameter {key!r}; valid: "
                f"{', '.join(sorted(SESSION_PARAMS))}")
        params[key] = value
    try:
        params["max_cycles"] = int(params["max_cycles"])
        params["segment_cycles"] = int(params["segment_cycles"])
        for key in ("damping", "stability", "noise", "slack"):
            params[key] = float(params[key])
        if params["decimation_margin"] is not None:
            margin = float(params["decimation_margin"])
            # margin <= 0 means OFF — the same contract as the
            # maxsum decimation_margin knob
            # (algorithms/maxsum.decimation_plan_from_params); a 0.0
            # must not mean "clamp everything" on one surface and
            # "disabled" on the other.
            params["decimation_margin"] = (margin if margin > 0
                                           else None)
    except (TypeError, ValueError) as exc:
        raise ValueError(f"bad session parameter value: {exc}")
    if params["segment_cycles"] <= 0 or params["max_cycles"] <= 0:
        raise ValueError(
            "max_cycles and segment_cycles must be positive")
    if params["damping_nodes"] not in _DAMPING_NODES:
        raise ValueError(
            f"damping_nodes must be one of {_DAMPING_NODES}, got "
            f"{params['damping_nodes']!r}")
    return params


def validate_events(events: Any) -> List[Dict[str, Any]]:
    """Shape-level wire validation of a PATCH event batch, on the
    submitting thread: the action types must be known and the
    per-action required keys present, so a malformed batch is a 400
    BEFORE it is journaled — never a scheduler-thread surprise.
    (Semantic errors — unknown factor names, scope mismatches — can
    only surface at apply time, against the engine state the batch
    actually meets; those turn the session's event SEQ into an error
    result instead.)"""
    if not isinstance(events, list) or not events:
        raise ValueError("events must be a non-empty list of actions")
    out = []
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise ValueError(f"event[{i}] must be an object")
        etype = ev.get("type")
        if etype not in EVENT_ACTIONS:
            raise ValueError(
                f"event[{i}] has unknown type {etype!r}; valid: "
                f"{', '.join(EVENT_ACTIONS)}")
        if etype in ("change_factor", "add_factor"):
            if not ev.get("name"):
                raise ValueError(f"event[{i}] ({etype}) needs 'name'")
            if "table" not in ev and "expression" not in ev:
                raise ValueError(
                    f"event[{i}] ({etype}) needs a 'table' or an "
                    "'expression'")
        elif etype == "remove_factor" and not ev.get("name"):
            raise ValueError(f"event[{i}] (remove_factor) needs 'name'")
        elif etype == "add_variable":
            if not ev.get("name") or not ev.get("domain"):
                raise ValueError(
                    f"event[{i}] (add_variable) needs 'name' and "
                    "'domain'")
        elif etype in ("remove_agent", "add_agent") \
                and not ev.get("agent"):
            raise ValueError(f"event[{i}] ({etype}) needs 'agent'")
        out.append(dict(ev))
    return out


def apply_event_batch(engine, events: Optional[List[Dict[str, Any]]]
                      ) -> "tuple[List[str], List[str], Optional[str]]":
    """Apply one wire-form action batch to an engine, in order,
    stopping at the first semantic failure (earlier actions STAND).

    This is the single definition of batch-apply semantics — the
    live path (:meth:`SessionManager._work_events`) and crash replay
    (:meth:`SessionManager._recover_one`) both call it, so a
    recovered session deterministically reproduces the engine state
    the live session had, INCLUDING partially-applied failed batches
    (divergent hand-rolled copies here were how live-tolerant /
    replay-fatal drift crept in).  Returns ``(applied_action_types,
    touched_variable_names, error_or_None)``.

    The whole batch applies under the engine's deferred-edit session
    (``DynamicMaxSumEngine.batch_edits``): per-bucket edits accumulate
    host-side and materialize as ONE copy per touched bucket per
    batch instead of one per action — behavior-identical (the flush
    runs even on the early error return, so earlier actions stand
    exactly as before), just without the per-action full-bucket
    copies the PR-13 note flagged."""
    applied: List[str] = []
    touched: List[str] = []
    ctx = (engine.batch_edits()
           if hasattr(engine, "batch_edits")
           else contextlib.nullcontext())
    try:
        with ctx:
            for action in events or []:
                args = {k: v for k, v in action.items()
                        if k != "type"}
                try:
                    info = apply_action(engine, action["type"], args)
                except Exception as exc:  # noqa: BLE001 — batch-
                    # scoped error: earlier actions stand.
                    return (applied, touched,
                            f"event apply failed: {exc}")
                touched.extend(info["touched"])
                applied.append(action["type"])
    except Exception as exc:  # noqa: BLE001 — a flush failure at
        # batch exit keeps the tuple contract too: the caller (live
        # work AND --recover replay) must get a batch error, never an
        # exception that aborts the whole session's replay.
        return applied, touched, f"event apply failed: {exc}"
    return applied, touched, None


def scenario_yaml_to_events(yaml_src: str) -> List[Dict[str, Any]]:
    """Flatten a dcop/scenario.py YAML script into one wire-form
    event batch (the ``PATCH`` body's ``"scenario"`` spelling):
    actions keep their order across events; delay events are dropped —
    a session's time base is its client's PATCH cadence, not the
    script's wall clock."""
    from pydcop_tpu.dcop.yamldcop import load_scenario

    events: List[Dict[str, Any]] = []
    for ev in load_scenario(yaml_src):
        if ev.is_delay:
            continue
        for action in ev.actions or []:
            events.append({"type": action.type, **action.args})
    return events


class SessionLimit(AdmissionRejected):
    """Too many live sessions: backpressure, not failure (429)."""

    http_status = 429


class SessionClosed(Exception):
    """Events/close against a terminal session (409 on the wire)."""


class StaleEpoch(SessionClosed):
    """An event batch carried an ownership epoch that doesn't match
    this replica's copy of the session (or the copy itself is
    FENCED).  A structured 409 on the wire — the split-brain guard:
    the client (or router) reconciles ownership instead of this
    replica double-applying what the real owner already owns."""

    def __init__(self, session_id: str, session_epoch: int,
                 request_epoch: Optional[int]):
        self.session_id = session_id
        self.session_epoch = int(session_epoch)
        self.request_epoch = (None if request_epoch is None
                              else int(request_epoch))
        super().__init__(
            f"session {session_id} ownership epoch is "
            f"{session_epoch}, request carried {request_epoch} — "
            "stale owner fenced; reconcile via the router")


@dataclass
class SolveSession:
    """One stateful solve: a warm engine plus its bookkeeping.

    The ENGINE is only ever touched on the scheduler thread
    (:meth:`SessionManager.run_work`); everything else is snapshotted
    under the manager lock."""

    id: str
    trace_id: str
    dcop_yaml: str
    params: Dict[str, Any]
    engine: Any
    status: str = OPEN
    # Ownership epoch: bumped by the fleet router on every repoint
    # (migration, dead-replica adoption), journaled with the open
    # record, checked against the epoch each forwarded event batch
    # carries.  1 for sessions that never moved (and for every
    # journal written before epochs existed).
    epoch: int = 1
    seq: int = 0            # acknowledged (journaled) event batches
    applied_seq: int = 0    # batches actually applied to the engine
    events_applied: int = 0  # individual actions applied
    recompiles: int = 0
    segments: int = 0
    budget: int = 0          # re-convergence cycles left, this activation
    last_cycle: int = 0
    events_since_ckpt: int = 0
    replayed: bool = False
    last: Optional[Dict[str, Any]] = None
    final: Optional[Dict[str, Any]] = None
    error: Optional[str] = None
    done: threading.Event = field(default_factory=threading.Event)
    subscribers: List["queue.Queue"] = field(default_factory=list)
    # In-memory copy of every acknowledged batch (seq/events/
    # trace_id), the migration-export fallback when the engine's
    # current problem can't be rebased to yaml: bundle = base problem
    # + this log.  Trimmed at every REBASED checkpoint (the base
    # advances past those batches), so it holds at most one
    # checkpoint interval of events — except on the rare rebase-
    # failure path, where it must keep the full tail.
    event_log: List[Dict[str, Any]] = field(default_factory=list)
    # Serializes seq-assign + journal append + enqueue for THIS
    # session: concurrent PATCHes must reach the journal and the
    # queue in seq order, or crash replay (which applies in seq
    # order) would reconstruct a different engine state than the
    # live process had.
    order_lock: threading.Lock = field(
        default_factory=threading.Lock)
    # Exact-certification oracle bookkeeping (docs/sessions.md): the
    # highest event seq whose quiesced fixpoint has been certified by
    # a background DPOP solve, and the seq a certify timer is already
    # pending for (both -1 initially so the seq-0 fixpoint — the
    # initial convergence before any event — is certifiable too).
    certified_seq: int = -1
    certify_scheduled_seq: int = -1


@dataclass
class SessionWork:
    """One unit of session work on the service queue.  The scheduler
    routes these to :meth:`SessionManager.run_work` between request
    flushes — session mutations and segments interleave with batched
    one-shot dispatches on the single device-owning thread."""

    kind: str   # "events" | "segment" | "close" | "export" | "certify"
    session: SolveSession
    events: Optional[List[Dict[str, Any]]] = None
    seq: int = 0
    trace_id: str = ""
    drain: bool = True       # close: run a final settle segment?
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[Dict[str, Any]] = None
    error: Optional[str] = None
    # Export drain: the work re-enqueued ITSELF behind acked event
    # batches still in the queue — run_work must not wake the waiter
    # yet (see _work_export).
    deferred: bool = False


class SessionManager:
    """Owns every live session of one SolveService.

    Open/close/event acks happen on submitting threads (journal
    appends included — the ack is durable before it is returned);
    engine work happens on the scheduler thread via :class:`SessionWork`
    items on the service queue.  ``max_sessions`` bounds live engines
    (each holds device arrays); past it, opens are 429s."""

    def __init__(self, service, max_sessions: int = 64,
                 segment_cycles: Optional[int] = None,
                 checkpoint_every_events: int = 8,
                 session_keep: int = 256,
                 certify_after: Optional[float] = None):
        self.service = service
        self.max_sessions = int(max_sessions)
        self.default_segment_cycles = segment_cycles
        self.checkpoint_every_events = int(checkpoint_every_events)
        # Exact-certification oracle: when set, a session whose event
        # stream has quiesced for this many seconds gets a background
        # DPOP solve of its CURRENT (mutated) problem on the scheduler
        # thread — certifying the warm fixpoint as optimal, or
        # replacing the served assignment with the true optimum.  None
        # disables the tier (the default: exact solves are not free).
        self.certify_after = (None if certify_after is None
                              else float(certify_after))
        self.certifications = 0
        self.certified_improved = 0
        self.certify_skipped_width = 0
        self.last_certification: Optional[Dict[str, Any]] = None
        # Terminal-session retention (the session analogue of the
        # service's result_keep): closed/errored sessions keep their
        # final result pollable until evicted oldest-first past this
        # bound — each tracked session pins a whole engine (device
        # arrays + compiled-program cache), so a long-lived service
        # must not retain every session it ever served.
        self.session_keep = int(session_keep)
        self._sessions: Dict[str, SolveSession] = {}
        self._lock = threading.Lock()
        self.opened = 0
        self.closed = 0
        self.errored = 0
        self.replayed_sessions = 0
        self.migrated_in = 0
        self.migrated_out = 0
        reg = metrics_registry
        self._active_g = reg.gauge(
            "pydcop_sessions_active",
            "Live stateful solve sessions")
        self._events_total = reg.counter(
            "pydcop_session_events_total",
            "Scenario-event actions applied to live sessions, by type")
        self._segments_total = reg.counter(
            "pydcop_session_segments_total",
            "Engine segments run on behalf of sessions")
        self._recompiles_total = reg.counter(
            "pydcop_session_recompiles_total",
            "Session engine recompiles (events that outgrew the "
            "shape envelope / slack budget)")
        self._sessions_total = reg.counter(
            "pydcop_sessions_total",
            "Session lifecycle outcomes (opened/closed/error/"
            "recovered)")

    # -- open / events / close (submitting threads) -------------------- #

    def open(self, dcop, params: Optional[Dict[str, Any]] = None,
             session_id: Optional[str] = None,
             trace_id: Optional[str] = None,
             epoch: int = 1) -> SolveSession:
        """Open a session: build the dynamic engine (host-side, on
        the calling thread — malformed problems fail synchronously as
        400s), journal the open record, enqueue the first
        convergence segment.  Returns the session; its id/trace_id
        are the client's handles."""
        if not self.service._started:
            raise RuntimeError("SolveService is not started")
        merged = normalize_session_params(params)
        if self.default_segment_cycles and "segment_cycles" not in (
                params or {}):
            merged["segment_cycles"] = int(self.default_segment_cycles)
        # Fast-path backpressure BEFORE the engine build: a saturated
        # service must reject opens cheaply, not pay a full
        # factor-graph construction per 429.  The authoritative
        # check-and-insert still happens under one lock hold below.
        with self._lock:
            live = sum(1 for s in self._sessions.values()
                       if s.status == OPEN)
            if live >= self.max_sessions:
                raise SessionLimit(
                    f"session limit reached ({self.max_sessions} "
                    "live)")
            if session_id and session_id in self._sessions:
                raise ValueError(
                    f"duplicate session id {session_id!r}")
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        engine = build_dynamic_engine(dcop, merged)
        # This engine's dispatches are session work: the efficiency
        # rollup's request class must say so (a scenario replay or
        # direct dynamic-engine use stays "dynamic").
        engine.efficiency_class = "session"
        yaml_src = dcop_yaml(dcop)
        sess = SolveSession(
            id=session_id or f"s{uuid.uuid4().hex[:12]}",
            trace_id=trace_id or uuid.uuid4().hex[:16],
            dcop_yaml=yaml_src,
            params=merged,
            engine=engine,
            budget=merged["max_cycles"],
            epoch=max(int(epoch), 1),
        )
        with self._lock:
            # Limit check and insert under ONE lock hold: a
            # check-then-insert race would let concurrent opens
            # overshoot max_sessions — exactly the warm-engine
            # resource bound the knob exists to enforce.
            live = sum(1 for s in self._sessions.values()
                       if s.status == OPEN)
            if live >= self.max_sessions:
                raise SessionLimit(
                    f"session limit reached ({self.max_sessions} "
                    "live)")
            if sess.id in self._sessions:
                raise ValueError(f"duplicate session id {sess.id!r}")
            self._sessions[sess.id] = sess
            self._prune_terminal_locked()
        journal = self.service._journal
        if journal is not None:
            # BEFORE the ack, exactly like submit(): the session id
            # this hands back must survive a process kill.
            try:
                journal.append(journal_mod.session_open_record(
                    sess.id, yaml_src, merged,
                    trace_id=sess.trace_id, epoch=sess.epoch))
                self.service._journal_records.inc(kind="session_open")
            except Exception as exc:
                with self._lock:
                    self._sessions.pop(sess.id, None)
                raise RuntimeError(
                    f"session journal append failed: {exc}") from exc
        self.opened += 1
        self._sessions_total.inc(status="opened")
        self._refresh_gauge()
        if tracer.active:
            tracer.instant("session_open", "serving",
                           session=sess.id, trace_id=sess.trace_id)
        self._publish(sess, "open")
        self._enqueue(SessionWork("segment", sess))
        return sess

    def apply_events(self, session_id: str,
                     events: List[Dict[str, Any]],
                     wait: Optional[float] = None,
                     epoch: Optional[int] = None,
                     trace_id: Optional[str] = None
                     ) -> Dict[str, Any]:
        """Acknowledge one event batch: validate (400s raise here),
        journal it (the ack is durable), enqueue the apply.  With
        ``wait`` (seconds), block for the post-event segment and
        include its result.  The returned ``seq`` is the batch's
        position in the session's event order.

        ``epoch`` (set on every router-forwarded batch) is the
        ownership fence: a mismatch against this replica's copy is a
        structured 409 (:class:`StaleEpoch`) — never an apply.  A
        direct client (no router, ``epoch=None``) skips the check."""
        sess = self._get(session_id)
        if sess.status == FENCED:
            raise StaleEpoch(session_id, sess.epoch, epoch)
        if sess.status != OPEN:
            raise SessionClosed(
                f"session {session_id} is {sess.status}")
        if epoch is not None and int(epoch) != sess.epoch:
            raise StaleEpoch(session_id, sess.epoch, epoch)
        events = validate_events(events)
        # A router-propagated batch context (ISSUE 20) is adopted as
        # this batch's trace id so the apply's spans land in the same
        # fleet trace as the router's forwarding instant; a direct
        # client's batch mints its own, as before.
        batch_trace = trace_id or uuid.uuid4().hex[:16]
        # seq assignment, journal append and enqueue are ONE atomic
        # step per session: with concurrent PATCHes (the front end is
        # a threading HTTP server) a later seq must never reach the
        # journal or the scheduler before an earlier one — replay
        # applies batches in seq order and must reconstruct exactly
        # the state the live engine had.  The journal write is a
        # flushed append (sub-ms); holding the per-session lock
        # across it also makes the failure rollback safe (no other
        # thread can have taken a later seq meanwhile).
        with sess.order_lock:
            # Re-check under the SAME lock a migration export uses to
            # freeze the session (and a fence uses to revoke it): a
            # batch acked after the export drained would be journaled
            # here but absent from the bundle — a lost acked event on
            # the target.  Holding order_lock makes freeze-vs-ack
            # atomic (409: the client retries against the new owner).
            if sess.status == FENCED:
                raise StaleEpoch(session_id, sess.epoch, epoch)
            if sess.status != OPEN:
                raise SessionClosed(
                    f"session {session_id} is {sess.status}")
            if epoch is not None and int(epoch) != sess.epoch:
                raise StaleEpoch(session_id, sess.epoch, epoch)
            with self._lock:
                sess.seq += 1
                seq = sess.seq
            journal = self.service._journal
            if journal is not None:
                try:
                    journal.append(journal_mod.session_event_record(
                        sess.id, seq, events, trace_id=batch_trace))
                    self.service._journal_records.inc(
                        kind="session_event")
                except Exception as exc:
                    with self._lock:
                        sess.seq -= 1
                    raise RuntimeError(
                        f"session journal append failed: {exc}"
                    ) from exc
            sess.event_log.append({"seq": seq, "events": events,
                                   "trace_id": batch_trace})
            work = SessionWork("events", sess, events=events,
                               seq=seq, trace_id=batch_trace)
            # Event work is an acked durable batch: it may WAIT for
            # queue room (the scheduler is draining it) but must
            # never be silently skipped — a dropped batch would make
            # the live engine diverge from the journal the 200
            # promises.  If the queue stays full past the block
            # window the whole session fails LOUDLY (journaled
            # close, so replay and live agree the batch never
            # applied) instead of serving divergent state.
            if not self._enqueue(work, block_s=30.0):
                self._fail(sess,
                           "service queue full; session failed "
                           "rather than skipping an acked event "
                           "batch")
                raise RuntimeError(
                    "service queue full: session event batch could "
                    "not be scheduled; session closed as ERROR")
        out = {
            "session_id": sess.id,
            "seq": seq,
            "trace_id": batch_trace,
            "events": len(events),
        }
        if wait:
            work.done.wait(wait)
            if work.done.is_set():
                out["applied"] = work.error is None
                if work.error is not None:
                    out["error"] = work.error
                if work.result is not None:
                    out["result"] = work.result
                out["recompiles"] = sess.recompiles
            else:
                out["applied"] = None  # still queued past the wait
        return out

    def close(self, session_id: str,
              wait: float = 60.0) -> Dict[str, Any]:
        """Close a session: a final settle segment runs, the close is
        journaled (the engine checkpoint file is retired with it) and
        the final result returned.  Closing a terminal session
        returns its existing final result (idempotent DELETEs)."""
        sess = self._get(session_id)
        if sess.status != OPEN:
            if sess.final is not None:
                return dict(sess.final)
            raise SessionClosed(
                f"session {session_id} is {sess.status}")
        work = SessionWork("close", sess)
        self._enqueue(work)
        work.done.wait(wait)
        if not work.done.is_set():
            raise TimeoutError(
                f"session {session_id} close timed out after "
                f"{wait}s")
        if work.error is not None and sess.final is None:
            raise RuntimeError(work.error)
        return dict(sess.final or {})

    # -- migration (serving/migration.py drives these) ----------------- #

    def export_session(self, session_id: str,
                       wait: float = 60.0) -> Dict[str, Any]:
        """Drain-checkpoint a session for migration and return its
        bundle.  The session is left MIGRATING: new PATCHes 409
        until :meth:`retire_session` (move succeeded) or
        :meth:`resume_session` (move failed) resolves it."""
        sess = self._get(session_id)
        if sess.status != OPEN:
            raise SessionClosed(
                f"session {session_id} is {sess.status}")
        work = SessionWork("export", sess)
        if not self._enqueue(work, block_s=10.0):
            raise RuntimeError(
                "service queue full: session export could not be "
                "scheduled")
        work.done.wait(wait)
        if not work.done.is_set():
            raise TimeoutError(
                f"session {session_id} export timed out after "
                f"{wait}s")
        if work.error is not None:
            raise RuntimeError(work.error)
        return work.result or {}

    def resume_session(self, session_id: str) -> Dict[str, Any]:
        """Un-freeze a MIGRATING session after a failed move: back to
        OPEN with a fresh re-convergence budget — the session must
        never have zero owners."""
        sess = self._get(session_id)
        with sess.order_lock:
            if sess.status != MIGRATING:
                raise SessionClosed(
                    f"session {session_id} is {sess.status}")
            sess.status = OPEN
            sess.budget = sess.params["max_cycles"]
        self._refresh_gauge()
        self._publish(sess, "resumed")
        self._enqueue(SessionWork("segment", sess))
        return {"session_id": sess.id, "status": OPEN}

    def retire_session(self, session_id: str,
                       moved_to: Optional[str] = None
                       ) -> Dict[str, Any]:
        """Finish a migration on the source side: journal a MIGRATED
        close (this segment's --recover must not resurrect what the
        target now owns), retire the checkpoint and end the SSE
        streams — subscribers get a terminal ``migrated`` event, then
        reconnect through the router and land on the new owner.
        Idempotent for already-MIGRATED sessions."""
        sess = self._get(session_id)
        with sess.order_lock:
            if sess.status == MIGRATED and sess.final is not None:
                return dict(sess.final)
            if sess.status != MIGRATING:
                raise SessionClosed(
                    f"session {session_id} is {sess.status}")
            sess.status = MIGRATED
        sess.final = {
            "session_id": sess.id,
            "trace_id": sess.trace_id,
            "status": MIGRATED,
        }
        if moved_to:
            sess.final["moved_to"] = moved_to
        self.migrated_out += 1
        self._sessions_total.inc(status="migrated")
        self._journal_close(sess, MIGRATED)
        self._retire_ckpt(sess)
        self._refresh_gauge()
        self._publish(sess, "migrated",
                      {"moved_to": moved_to} if moved_to else None)
        sess.done.set()
        return dict(sess.final)

    def fence_session(self, session_id: str,
                      epoch: int) -> Dict[str, Any]:
        """Revoke this replica's copy of a session whose ownership
        moved while the replica was partitioned/presumed dead:
        terminal FENCED, journaled (this segment's ``--recover`` must
        not resurrect the stale copy), checkpoint retired, SSE
        subscribers get a terminal ``fenced`` event — they reconnect
        through the router and land on the real owner.  Idempotent;
        a fence carrying an epoch BELOW this copy's is itself stale
        and rejected (:class:`StaleEpoch`)."""
        sess = self._get(session_id)
        epoch = int(epoch)
        with sess.order_lock:
            if sess.status == FENCED:
                return dict(sess.final or {"session_id": sess.id,
                                           "status": FENCED})
            if epoch < sess.epoch:
                raise StaleEpoch(session_id, sess.epoch, epoch)
            if sess.status not in (OPEN, MIGRATING, REPLAYABLE):
                # Terminal already: the copy can't serve writes, so
                # there is nothing left to fence.
                return {"session_id": sess.id,
                        "status": sess.status}
            sess.status = FENCED
            # Record the epoch that outranked this copy: a later
            # stale write gets told how far behind it is.
            sess.epoch = max(sess.epoch, epoch)
        sess.final = {
            "session_id": sess.id,
            "trace_id": sess.trace_id,
            "status": FENCED,
            "epoch": epoch,
        }
        self._sessions_total.inc(status="fenced")
        self._journal_close(sess, FENCED)
        self._retire_ckpt(sess)
        self._refresh_gauge()
        self._publish(sess, "fenced", {"epoch": epoch})
        sess.done.set()
        logger.info("session %s fenced at epoch %d (stale copy "
                    "revoked)", sess.id, epoch)
        return dict(sess.final)

    def status(self, session_id: str) -> Dict[str, Any]:
        sess = self._get(session_id)
        with self._lock:
            out = {
                "session_id": sess.id,
                "trace_id": sess.trace_id,
                "status": sess.status,
                "epoch": sess.epoch,
                "seq": sess.seq,
                "applied_seq": sess.applied_seq,
                "events_applied": sess.events_applied,
                "recompiles": sess.recompiles,
                "segments": sess.segments,
                "cycles": sess.last_cycle,
                "clamped": len(sess.engine.clamps),
                "replayed": sess.replayed,
                "last": dict(sess.last) if sess.last else None,
            }
            if sess.final is not None:
                out["final"] = dict(sess.final)
            if sess.error is not None:
                out["error"] = sess.error
        return out

    def _get(self, session_id: str) -> SolveSession:
        with self._lock:
            sess = self._sessions.get(session_id)
        if sess is None:
            raise KeyError(session_id)
        return sess

    def _prune_terminal_locked(self) -> None:
        """Evict oldest TERMINAL sessions (and their engines) past
        ``session_keep``; live sessions are never evicted — their
        clients still hold the id.  Caller holds the lock."""
        excess = len(self._sessions) - self.session_keep
        if excess <= 0:
            return
        # MIGRATING is live-adjacent, not terminal: its client still
        # holds the id and the move may resolve back to OPEN.
        for sid in [sid for sid, s in self._sessions.items()
                    if s.status not in (OPEN, MIGRATING)][:excess]:
            del self._sessions[sid]

    def _enqueue(self, work: SessionWork,
                 block_s: Optional[float] = None) -> bool:
        """Queue one work item.  ``block_s=None`` (segments, close,
        recovery kick-offs) never blocks: that work is re-creatable —
        a dropped continuation segment resumes at the next PATCH and
        a --recover restart rebuilds everything.  Acked EVENT batches
        pass a block window instead (see :meth:`apply_events`) —
        they are the one kind that must not be skipped.  Returns
        whether the item was queued."""
        try:
            if block_s is None:
                self.service._queue.put_nowait(work)
            else:
                self.service._queue.put(work, timeout=block_s)
            return True
        except queue.Full:
            logger.warning(
                "service queue full: session %s %s work dropped",
                work.session.id, work.kind)
            work.error = "service queue full"
            work.done.set()
            return False

    # -- SSE ----------------------------------------------------------- #

    def subscribe(self, session_id: str) -> "queue.Queue":
        """Per-session SSE feed: replays the latest segment event on
        connect, then streams every subsequent segment/terminal
        event."""
        sess = self._get(session_id)
        q: "queue.Queue" = queue.Queue(maxsize=256)
        with self._lock:
            sess.subscribers.append(q)
            replay = sess.final or sess.last
        if replay is not None:
            with contextlib.suppress(queue.Full):
                q.put_nowait(dict(replay))
        return q

    def unsubscribe(self, session_id: str, q: "queue.Queue") -> None:
        with self._lock:
            sess = self._sessions.get(session_id)
            if sess is not None and q in sess.subscribers:
                sess.subscribers.remove(q)

    def _publish(self, sess: SolveSession, phase: str,
                 payload: Optional[Dict[str, Any]] = None) -> None:
        """One session-lifecycle event: to the session's own SSE
        subscribers (full payload, anytime assignment included), to
        the global /events stream (compact — no assignment), and as a
        trace instant when tracing is on."""
        event = {
            "ts": time.time(),
            "event": "session",
            "phase": phase,
            "id": sess.id,
            "trace_id": sess.trace_id,
            "status": sess.status,
            "seq": sess.seq,
        }
        if payload:
            event.update(payload)
        with self._lock:
            if phase in ("segment", "closed", "error", "replayable"):
                if phase == "segment":
                    sess.last = dict(event)
            elif phase == "certified" and "assignment" in event:
                # An improving certification REPLACES the served
                # anytime answer in place: merge the exact
                # cost/assignment over the last segment event (the
                # SSE replay-on-connect and close paths read
                # ``sess.last``) without touching the warm engine —
                # no recompile, and the next event batch resumes the
                # iterative fixpoint exactly where it was.
                merged = dict(sess.last or {})
                merged.update({k: event[k] for k in (
                    "assignment", "cost", "optimal",
                    "certified_seq") if k in event})
                sess.last = merged
            subscribers = list(sess.subscribers)
        for q in subscribers:
            try:
                q.put_nowait(dict(event))
            except queue.Full:
                with contextlib.suppress(queue.Empty, queue.Full):
                    q.get_nowait()
                    q.put_nowait(dict(event))
        # The global stream is compact: no assignment, top-level OR
        # nested (the closed event's "final" dict carries one too).
        compact = {k: v for k, v in event.items()
                   if k != "assignment"}
        if isinstance(compact.get("final"), dict):
            compact["final"] = {
                k: v for k, v in compact["final"].items()
                if k != "assignment"}
        CycleSnapshotter.publish(compact)
        if tracer.active:
            tracer.instant(f"session_{phase}", "serving",
                           session=sess.id, trace_id=sess.trace_id)

    # -- scheduler-thread work ----------------------------------------- #

    def run_work(self, work: SessionWork) -> None:
        """Execute one session work item (scheduler thread only).
        Bound into the session's trace context so every span the
        engine records underneath — ``jit_compile``, engine calls —
        is attributable to the session like a one-shot request's
        dispatch spans."""
        sess = work.session
        # MIGRATING still runs "events" (acked batches queued before
        # the export freeze MUST apply — the export re-enqueues
        # itself behind them) and "export" itself; everything else
        # needs OPEN.
        allowed = (sess.status == OPEN
                   or (sess.status == MIGRATING
                       and work.kind in ("events", "export")))
        if not allowed:
            work.error = f"session is {sess.status}"
            work.done.set()
            return
        ids = [sess.trace_id]
        if work.trace_id:
            ids.append(work.trace_id)
        ctx = (tracer.context(trace_ids=ids)
               if tracer.active else contextlib.nullcontext())
        try:
            with ctx:
                if work.kind == "events":
                    self._work_events(work)
                elif work.kind == "segment":
                    self._work_segment(sess)
                elif work.kind == "close":
                    self._work_close(work)
                elif work.kind == "export":
                    self._work_export(work)
                elif work.kind == "certify":
                    self._work_certify(work)
                else:
                    raise ValueError(
                        f"unknown session work {work.kind!r}")
        except Exception as exc:  # noqa: BLE001 — fail the session,
            # never the scheduler thread.
            logger.exception("session %s %s work failed",
                             sess.id, work.kind)
            self._fail(sess, f"{work.kind} failed: {exc}")
            work.error = str(exc)
        finally:
            if not work.deferred:
                work.done.set()

    def _work_events(self, work: SessionWork) -> None:
        """Apply one acknowledged batch between segments: array
        surgery + clamp release on touched variables, then an
        immediate re-convergence segment (the PATCH ``wait`` answer).
        A semantically-bad action (unknown factor, scope mismatch)
        fails THIS batch — the session survives, already-applied
        actions of the batch stand (:func:`apply_event_batch`; crash
        replay reapplies through the same helper, so the recovered
        engine state matches even for failed batches), and the
        post-batch segment still runs — a partially-applied batch
        must not leave the session serving the stale pre-event
        assignment."""
        sess = work.session
        span = (tracer.span("session_events", "serving",
                            session=sess.id, seq=work.seq,
                            n_actions=len(work.events or []))
                if tracer.active else None)
        with (span if span is not None else contextlib.nullcontext()):
            before = sess.engine.recompile_count
            applied, touched, error = apply_event_batch(
                sess.engine, work.events)
            for action_type in applied:
                self._events_total.inc(type=action_type)
            sess.events_applied += len(applied)
            recompiled = sess.engine.recompile_count - before
            sess.recompiles += recompiled
            if recompiled:
                self._recompiles_total.inc(recompiled)
            if error is not None:
                work.error = error
                logger.warning("session %s event batch %d: %s",
                               sess.id, work.seq, error)
                self._publish(sess, "event_error", {
                    "batch_seq": work.seq, "error": error})
            if touched:
                # The event re-opened exactly this neighborhood;
                # clamps elsewhere keep their decided values.
                sess.engine.release_clamps(touched)
            sess.applied_seq = work.seq
            sess.events_since_ckpt += 1
            sess.budget = sess.params["max_cycles"]
        self._maybe_checkpoint(sess)
        work.result = self._run_segment(sess, batch_seq=work.seq)
        self._continue(sess)

    def _work_segment(self, sess: SolveSession) -> None:
        self._run_segment(sess)
        self._continue(sess)

    def _run_segment(self, sess: SolveSession,
                     batch_seq: Optional[int] = None
                     ) -> Dict[str, Any]:
        """One warm engine segment + the anytime publication."""
        # Always a FULL segment_cycles: max_cycles is part of the
        # superstep program's jit key, so sizing the last segment to
        # the budget remainder would compile a second program per
        # shape (seconds on TPU) to save at most one segment's
        # cycles — the budget is enforced host-side instead, and may
        # overshoot by less than one segment.
        seg = sess.params["segment_cycles"]
        t_seg = time.perf_counter()
        span = (tracer.span("session_segment", "serving",
                            session=sess.id, cycles=seg)
                if tracer.active else None)
        with (span if span is not None else contextlib.nullcontext()):
            res = sess.engine.run(max_cycles=seg)
            cost = sess.engine.cost(res.assignment)
        t_seg_end = time.perf_counter()
        ran = max(res.cycles - sess.last_cycle, 0)
        sess.last_cycle = res.cycles
        sess.budget = max(sess.budget - max(ran, seg), 0)
        sess.segments += 1
        self._segments_total.inc()
        if (res.converged
                and sess.params["decimation_margin"] is not None):
            sess.engine.decimate(
                margin=sess.params["decimation_margin"])
        # Segment time ledger (the session face of the request
        # ledger): device compile/execute from the engine's
        # overlapping-fields split, everything else in the segment
        # wall — assignment decode + host cost evaluation — is
        # ``decode``.  Components sum to the measured segment wall.
        from pydcop_tpu.observability import efficiency

        split = efficiency.split_device_time(
            res.time_s, res.compile_time_s)
        ledger = efficiency.make_ledger(
            t_seg_end - t_seg,
            compile=split["compile"],
            execute=split["execute"],
            decode=max((t_seg_end - t_seg) - res.time_s, 0.0),
        )
        efficiency.tracker.record_ledger(ledger, kind="session")
        payload = {
            "cycle": res.cycles,
            "cost": cost,
            "converged": res.converged,
            "assignment": res.assignment,
            "recompiles": sess.recompiles,
            "clamped": len(sess.engine.clamps),
            "ledger": ledger,
        }
        if batch_seq is not None:
            payload["batch_seq"] = batch_seq
        self._publish(sess, "segment", payload)
        return payload

    def _continue(self, sess: SolveSession) -> None:
        """Re-enqueue the session while it still has re-convergence
        budget and has not converged — segments interleave with other
        traffic instead of monopolizing the scheduler."""
        if sess.status != OPEN:
            return
        last = sess.last or {}
        if last.get("converged") or sess.budget <= 0:
            # Quiesced: the warm fixpoint is what clients will be
            # served until the next event.  If the oracle tier is on,
            # arm the certification timer — a fresh event batch
            # before it fires advances applied_seq and the stale
            # certify work no-ops.
            self._maybe_schedule_certify(sess)
            return
        self._enqueue(SessionWork("segment", sess))

    def _maybe_schedule_certify(self, sess: SolveSession) -> None:
        if self.certify_after is None or sess.status != OPEN:
            return
        target = sess.applied_seq
        if sess.certified_seq >= target \
                or sess.certify_scheduled_seq >= target:
            return
        sess.certify_scheduled_seq = target

        def _fire():
            # Timer thread: only enqueue (put_nowait is thread-safe);
            # all engine work stays on the scheduler thread.
            self._enqueue(SessionWork("certify", sess, seq=target))

        timer = threading.Timer(self.certify_after, _fire)
        timer.daemon = True
        timer.start()

    def _work_close(self, work: SessionWork) -> None:
        sess = work.session
        last = sess.last
        if last is None or (work.drain and not last.get("converged")
                            and sess.budget > 0):
            last = self._run_segment(sess)
        sess.final = {
            "session_id": sess.id,
            "trace_id": sess.trace_id,
            "status": CLOSED,
            "assignment": last.get("assignment"),
            "cost": last.get("cost"),
            "cycles": last.get("cycle"),
            "converged": last.get("converged"),
            "events_applied": sess.events_applied,
            "event_batches": sess.applied_seq,
            "recompiles": sess.recompiles,
            "segments": sess.segments,
        }
        sess.status = CLOSED
        self.closed += 1
        self._sessions_total.inc(status="closed")
        self._journal_close(sess, CLOSED)
        self._retire_ckpt(sess)
        self._refresh_gauge()
        self._publish(sess, "closed", {"final": dict(sess.final)})
        work.result = sess.final
        sess.done.set()

    def _work_export(self, work: SessionWork) -> None:
        """Drain-checkpoint the session into a migration bundle
        (scheduler thread).  Freeze first (new acks 409 under the
        same order_lock apply_events holds), then make sure every
        ALREADY-acked batch has applied: if any are still queued
        behind this work, re-enqueue ourselves after them
        (``deferred`` keeps the waiter blocked) — the freeze bounds
        the loop to the batches acked before it.  Any failure resumes
        the session: a failed export must never cost an owner."""
        sess = work.session
        work.deferred = False
        with sess.order_lock:
            if sess.status not in (OPEN, MIGRATING):
                work.error = f"session is {sess.status}"
                return
            sess.status = MIGRATING
            if sess.applied_seq != sess.seq:
                work.deferred = True
                if not self._enqueue(work):
                    work.deferred = False
                    work.error = ("service queue full during export "
                                  "drain")
                    sess.status = OPEN
                return
        try:
            from pydcop_tpu.serving import migration as migration_mod

            rebased = None
            try:
                rebased = migration_mod.engine_dcop_yaml(
                    sess.engine, name=f"session_{sess.id}")
            except Exception as exc:  # noqa: BLE001 — fall back to
                # base problem + the acked-batch log.
                logger.info(
                    "session %s: problem rebase failed (%s); "
                    "bundling base problem + %d event batch(es)",
                    sess.id, exc, len(sess.event_log))
            npz_bytes = None
            ckpt_seq = None
            if self.checkpoint_session(sess, rebased_yaml=rebased):
                path = self._ckpt_path(sess)
                with contextlib.suppress(OSError):
                    with open(path, "rb") as f:
                        npz_bytes = f.read()
            elif sess.engine._state is not None:
                # Journal-less service: snapshot straight into the
                # bundle via a throwaway tmp file.
                fd, tmp = tempfile.mkstemp(suffix=".npz")
                os.close(fd)
                try:
                    sess.engine.checkpoint(tmp)
                    with open(tmp, "rb") as f:
                        npz_bytes = f.read()
                except Exception as exc:  # noqa: BLE001 — a cold
                    # import beats a failed migration.
                    logger.warning(
                        "session %s: export snapshot failed (%s); "
                        "bundle ships without warm state",
                        sess.id, exc)
                finally:
                    with contextlib.suppress(OSError):
                        os.unlink(tmp)
            if npz_bytes is not None:
                ckpt_seq = sess.applied_seq
            work.result = migration_mod.build_bundle(
                sess.id, sess.trace_id,
                rebased or sess.dcop_yaml,
                rebased=rebased is not None,
                params=sess.params,
                seq=sess.seq,
                cycle=sess.last_cycle,
                events=(None if rebased is not None
                        else list(sess.event_log)),
                npz_bytes=npz_bytes,
                ckpt_seq=ckpt_seq,
                epoch=sess.epoch,
            )
            self._publish(sess, "migrating")
        except Exception as exc:  # noqa: BLE001
            logger.exception("session %s export failed", sess.id)
            work.error = f"export failed: {exc}"
            with sess.order_lock:
                if sess.status == MIGRATING:
                    sess.status = OPEN
            self._enqueue(SessionWork("segment", sess))

    def _work_certify(self, work: SessionWork) -> None:
        """The session oracle (scheduler thread): an exact DPOP solve
        of the session's CURRENT mutated problem, run only after the
        event stream quiesced for ``certify_after`` seconds.  Either
        certifies the warm fixpoint as optimal (delta 0) or replaces
        the served assignment with the true optimum — in both cases
        the certified delta goes to the session SSE stream and the
        /stats rollup.  Failures degrade to a log line: the oracle is
        an accuracy tier, never allowed to kill a healthy session."""
        sess = work.session
        if sess.applied_seq != work.seq or sess.certified_seq >= work.seq:
            # Stale: new events arrived while the timer ran (their
            # quiescence re-arms with a newer seq), or a concurrent
            # timer already certified this seq.
            return
        last = sess.last or {}
        fixpoint_cost = last.get("cost")
        if fixpoint_cost is None:
            return
        t0 = time.perf_counter()
        try:
            from pydcop_tpu.computations_graph import pseudotree as pt
            from pydcop_tpu.dcop.yamldcop import load_dcop
            from pydcop_tpu.engine.dpop import (
                DpopEngine,
                dpop_feasibility,
            )
            from pydcop_tpu.serving import migration as migration_mod

            # Rebase the engine's live problem (event surgery
            # included) back to a DCOP — the same round-trip the
            # migration exporter uses.  Unrebasable problems skip
            # certification rather than certifying the wrong problem.
            yaml_src = migration_mod.engine_dcop_yaml(
                sess.engine, name=f"certify_{sess.id}")
            dcop = load_dcop(yaml_src)
            tree = pt.build_computation_graph(dcop)
            verdict = dpop_feasibility(tree, mode=dcop.objective,
                                       cec=True)
            if not verdict["feasible"]:
                self.certify_skipped_width += 1
                self._publish(sess, "certify_skipped", {
                    "reason": "rejected_width",
                    "induced_width": verdict["induced_width"],
                    "max_elements": (verdict["cec_max_elements"]
                                     or verdict["max_elements"]),
                })
                return
            span = (tracer.span("session_certify", "serving",
                                session=sess.id, seq=work.seq)
                    if tracer.active else None)
            with (span if span is not None
                  else contextlib.nullcontext()):
                res = DpopEngine(tree, mode=dcop.objective,
                                 cec=True).run()
                # Score the exact assignment with the ENGINE's cost
                # function — the same scale every published segment
                # cost uses, so the delta below is apples-to-apples.
                exact_cost = sess.engine.cost(res.assignment)
            delta = (float(fixpoint_cost) - float(exact_cost)
                     if dcop.objective == "min"
                     else float(exact_cost) - float(fixpoint_cost))
            improved = delta > 1e-9
            sess.certified_seq = work.seq
            self.certifications += 1
            if improved:
                self.certified_improved += 1
            payload: Dict[str, Any] = {
                "certified_seq": work.seq,
                "certified_cost": exact_cost,
                "fixpoint_cost": fixpoint_cost,
                "delta": delta,
                "optimal": True,
                "improved": improved,
                "induced_width": res.metrics.get("induced_width"),
                "certify_s": time.perf_counter() - t0,
            }
            if improved:
                # _publish folds the exact assignment + cost into
                # sess.last — the served answer upgrades in place,
                # the warm engine is untouched.
                payload["assignment"] = res.assignment
                payload["cost"] = exact_cost
            self.last_certification = {
                "session": sess.id, "seq": work.seq,
                "delta": delta, "improved": improved,
                "certified_cost": exact_cost,
                "fixpoint_cost": fixpoint_cost,
            }
            work.result = dict(payload)
            self._publish(sess, "certified", payload)
        except Exception as exc:  # noqa: BLE001 — oracle failures
            # must not take the session down with them.
            logger.warning("session %s certification failed: %s",
                           sess.id, exc)

    def _fail(self, sess: SolveSession, message: str) -> None:
        sess.error = message
        sess.status = ERROR
        sess.final = {
            "session_id": sess.id, "trace_id": sess.trace_id,
            "status": ERROR, "error": message,
        }
        self.errored += 1
        self._sessions_total.inc(status="error")
        self._journal_close(sess, ERROR)
        self._retire_ckpt(sess)
        self._refresh_gauge()
        flight.trigger("session_error", session=sess.id,
                       trace_id=sess.trace_id, error=message)
        self._publish(sess, "error", {"error": message})
        sess.done.set()

    def _journal_close(self, sess: SolveSession, status: str) -> None:
        journal = self.service._journal
        if journal is None:
            return
        try:
            journal.append(journal_mod.session_close_record(
                sess.id, status))
            self.service._journal_records.inc(kind="session_close")
        except Exception as exc:  # noqa: BLE001 — at most one
            # duplicate replay after a crash, never a dead service.
            logger.warning("session close journal append failed for "
                           "%s: %s", sess.id, exc)

    # -- checkpoint / recovery ----------------------------------------- #

    def _ckpt_path(self, sess: SolveSession) -> Optional[str]:
        if not self.service.journal_dir:
            return None
        return os.path.join(self.service.journal_dir,
                            f"session_{sess.id}.npz")

    def checkpoint_session(self, sess: SolveSession,
                           rebased_yaml: Any = _UNSET) -> bool:
        """Snapshot the engine's warm message state next to the
        journal (tmp+rename — a crash mid-write leaves the previous
        snapshot) and journal the marker.  Returns True when a
        checkpoint landed.  Only meaningful on the scheduler thread
        (or after it stopped: the stop() park path).

        The marker is REBASED whenever the engine's current problem
        serializes back to yaml (serving/migration.engine_dcop_yaml):
        recovery then rebuilds the factor layout from the marker
        alone and compaction drops the pre-checkpoint event tail —
        replay time is bounded by the checkpoint cadence, not session
        age (the ISSUE-16 recovery bound).  Pass ``rebased_yaml``
        (a yaml string, or None for a plain marker) to skip the
        recompute when the caller already serialized it."""
        path = self._ckpt_path(sess)
        if path is None or sess.engine._state is None:
            return False
        if rebased_yaml is _UNSET:
            try:
                from pydcop_tpu.serving import (
                    migration as migration_mod)

                rebased_yaml = migration_mod.engine_dcop_yaml(
                    sess.engine, name=f"session_{sess.id}")
            except Exception as exc:  # noqa: BLE001 — a plain
                # (un-rebased) marker is the pre-ISSUE-16 behavior:
                # strictly worse replay time, never worse
                # correctness.
                logger.info(
                    "session %s: checkpoint rebase failed (%s); "
                    "writing a plain marker", sess.id, exc)
                rebased_yaml = None
        # np.savez appends ".npz" to names without it: the tmp name
        # must already end in .npz or the rename source won't exist.
        tmp = path + ".tmp.npz"
        try:
            sess.engine.checkpoint(tmp)
            os.replace(tmp, path)
            journal = self.service._journal
            if journal is not None:
                journal.append(journal_mod.session_ckpt_record(
                    sess.id, sess.applied_seq, path,
                    cycle=sess.last_cycle, dcop=rebased_yaml))
                self.service._journal_records.inc(
                    kind="session_ckpt")
        except Exception as exc:  # noqa: BLE001 — a failed snapshot
            # costs replay time after a crash, never the session.
            logger.warning("session %s checkpoint failed: %s",
                           sess.id, exc)
            with contextlib.suppress(OSError):
                os.unlink(tmp)
            return False
        sess.events_since_ckpt = 0
        if rebased_yaml:
            # The base problem advanced past every batch through
            # applied_seq: the in-memory fallback log (and the
            # export-bundle base) advance with it.  order_lock —
            # apply_events appends to the log under it, so the
            # filter-and-replace can't drop a concurrent ack.
            with sess.order_lock:
                sess.dcop_yaml = rebased_yaml
                sess.event_log[:] = [
                    r for r in sess.event_log
                    if r.get("seq", 0) > sess.applied_seq]
        return True

    def _maybe_checkpoint(self, sess: SolveSession) -> None:
        if (self.checkpoint_every_events > 0
                and sess.events_since_ckpt
                >= self.checkpoint_every_events):
            self.checkpoint_session(sess)

    def _retire_ckpt(self, sess: SolveSession) -> None:
        path = self._ckpt_path(sess)
        if path:
            with contextlib.suppress(OSError):
                os.unlink(path)

    def recover(self, pending: List[Dict[str, Any]]) -> int:
        """Resume journaled sessions after a crash (service start,
        ``--recover``): rebuild each engine from the open record,
        re-apply the pre-checkpoint event batches STRUCTURALLY (the
        factor layout must match before message state can land),
        restore the checkpointed messages when a valid snapshot
        exists (cold-start warmup otherwise — correctness never
        depends on the checkpoint), apply the journaled-but-unapplied
        batches, and enqueue a re-convergence segment.  Decimation
        clamps are NOT restored — recovery re-converges unclamped,
        which costs cycles, never correctness."""
        from pydcop_tpu.dcop.yamldcop import load_dcop

        recovered = 0
        if pending:
            flight.trigger("session_replay", n_sessions=len(pending))
        span = (tracer.span("session_replay", "serving",
                            n_sessions=len(pending))
                if tracer.active and pending else None)
        with (span if span is not None else contextlib.nullcontext()):
            for rec in pending:
                open_rec = rec["open"]
                sid = open_rec.get("id")
                try:
                    sess = self._recover_one(
                        load_dcop, open_rec, rec.get("ckpt"),
                        rec.get("events") or [])
                except Exception as exc:  # noqa: BLE001 — one bad
                    # session must not abort the rest of the replay.
                    logger.warning(
                        "session replay failed for %s: %s", sid, exc)
                    journal = self.service._journal
                    if journal is not None and sid:
                        with contextlib.suppress(Exception):
                            journal.append(
                                journal_mod.session_close_record(
                                    sid, ERROR))
                    continue
                recovered += 1
                if tracer.active:
                    tracer.instant("session_replay_session",
                                   "serving", session=sess.id,
                                   trace_id=sess.trace_id)
        self.replayed_sessions += recovered
        if recovered:
            self._sessions_total.inc(recovered, status="recovered")
            logger.info("session recovery resumed %d session(s)",
                        recovered)
        self._refresh_gauge()
        return recovered

    def _recover_one(self, load_dcop, open_rec, ckpt_rec,
                     event_recs) -> SolveSession:
        # A REBASED checkpoint marker carries the session's problem
        # as of its seq (engine_dcop_yaml): the factor layout
        # rebuilds from the marker alone and the pre-checkpoint
        # batches (already dropped by journal compaction) never
        # replay — recovery work is bounded by the checkpoint
        # cadence, not session age.
        base_yaml = (ckpt_rec or {}).get("dcop") or open_rec["dcop"]
        dcop = load_dcop(base_yaml)
        params = normalize_session_params(
            open_rec.get("params") or {})
        engine = build_dynamic_engine(dcop, params)
        engine.efficiency_class = "session"
        sess = SolveSession(
            id=open_rec["id"],
            trace_id=(open_rec.get("trace_id")
                      or uuid.uuid4().hex[:16]),
            dcop_yaml=base_yaml,
            params=params,
            engine=engine,
            budget=params["max_cycles"],
            replayed=True,
            epoch=max(int(open_rec.get("epoch") or 1), 1),
        )
        ckpt_seq = (ckpt_rec or {}).get("seq", -1)
        pre = [r for r in event_recs
               if r.get("seq", 0) <= ckpt_seq]
        post = [r for r in event_recs
                if r.get("seq", 0) > ckpt_seq]
        applied = 0
        # Batches replay through the SAME apply_event_batch the live
        # path used, with the same tolerance: a batch that failed
        # semantically in live operation fails identically here
        # (earlier actions stand, later batches still apply) — the
        # recovered engine state matches the crashed process's, and
        # one bad batch can never void the durable 200s that
        # followed it.
        for rec in pre:
            batch_applied, _touched, error = apply_event_batch(
                engine, rec.get("events"))
            applied += len(batch_applied)
            if error is not None:
                logger.warning(
                    "session %s replay: batch %s failed as it did "
                    "live: %s", sess.id, rec.get("seq"), error)
        if ckpt_rec is not None:
            try:
                engine.restore(ckpt_rec["path"])
                sess.last_cycle = int(ckpt_rec.get("cycle", 0))
            except Exception as exc:  # noqa: BLE001 — a bad snapshot
                # degrades to a cold warm-up, never kills the replay.
                logger.warning(
                    "session %s checkpoint restore failed (%s); "
                    "re-converging cold", sess.id, exc)
        for rec in post:
            batch_applied, touched, error = apply_event_batch(
                engine, rec.get("events"))
            applied += len(batch_applied)
            if error is not None:
                logger.warning(
                    "session %s replay: batch %s failed as it did "
                    "live: %s", sess.id, rec.get("seq"), error)
            if touched:
                engine.release_clamps(touched)
        # Every journaled batch was processed (applied or failed
        # batch-scoped, same as live): both counters land on the max
        # journaled seq.
        sess.seq = max(
            [r.get("seq", 0) for r in event_recs]
            + [(ckpt_rec or {}).get("seq", 0)] or [0])
        sess.applied_seq = sess.seq
        sess.events_applied = applied
        # Seed the migration-export fallback log with the batches
        # the base problem does NOT already include.
        base_seq = ((ckpt_rec or {}).get("seq", 0)
                    if (ckpt_rec or {}).get("dcop") else -1)
        sess.event_log = [
            {"seq": r.get("seq", 0), "events": r.get("events") or [],
             "trace_id": r.get("trace_id", "")}
            for r in event_recs if r.get("seq", 0) > base_seq]
        with self._lock:
            self._sessions[sess.id] = sess
        self._publish(sess, "open", {"replayed": True})
        self._enqueue(SessionWork("segment", sess))
        return sess

    # -- shutdown ------------------------------------------------------ #

    def park_all(self) -> int:
        """Service stop: checkpoint every OPEN session's warm state
        (a --recover restart resumes from it instead of re-converging
        cold) and mark it REPLAYABLE (journaled services) or ERROR
        (journal-less — the state is genuinely gone).  Wakes every
        waiter.  Returns the parked-session count.  Runs after the
        scheduler halted, so touching the engines is safe."""
        with self._lock:
            # MIGRATING parks too: a stop mid-migration leaves the
            # journal authoritative — no close record was written, so
            # a --recover restart resumes the session here (worst
            # case the target ALSO imported it; the router pin
            # decides the owner).
            open_sessions = [s for s in self._sessions.values()
                             if s.status in (OPEN, MIGRATING)]
        journaled = self.service._journal is not None
        for sess in open_sessions:
            if journaled:
                self.checkpoint_session(sess)
                sess.status = REPLAYABLE
                sess.final = {
                    "session_id": sess.id,
                    "trace_id": sess.trace_id,
                    "status": REPLAYABLE,
                    "error": "service stopped; session journaled "
                             "for --recover replay",
                }
                self._publish(sess, "replayable")
            else:
                self._fail(sess, "service stopped with the session "
                                 "open (no journal to replay from)")
                continue
            sess.done.set()
        self._refresh_gauge()
        return len(open_sessions)

    # -- introspection ------------------------------------------------- #

    def active_count(self) -> int:
        with self._lock:
            return sum(1 for s in self._sessions.values()
                       if s.status == OPEN)

    def _refresh_gauge(self) -> None:
        self._active_g.set(self.active_count())

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            live = [s for s in self._sessions.values()
                    if s.status == OPEN]
            return {
                "active": len(live),
                "opened": self.opened,
                "closed": self.closed,
                "errored": self.errored,
                "replayed": self.replayed_sessions,
                "migrated_in": self.migrated_in,
                "migrated_out": self.migrated_out,
                "max_sessions": self.max_sessions,
                "events_applied": sum(
                    s.events_applied
                    for s in self._sessions.values()),
                "recompiles": sum(
                    s.recompiles for s in self._sessions.values()),
                # The oracle tier's rollup (docs/sessions.md): how
                # many quiesced fixpoints were certified, how many
                # certifications IMPROVED the served answer, and the
                # most recent certified-cost delta.
                "certify_after": self.certify_after,
                "certifications": self.certifications,
                "certified_improved": self.certified_improved,
                "certify_skipped_width": self.certify_skipped_width,
                "last_certification": (
                    dict(self.last_certification)
                    if self.last_certification else None),
            }

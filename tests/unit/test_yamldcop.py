"""YAML loader tests, including round-trips on the committed local
instances and — when mounted — the reference's own fixture files.

The reference fixtures are the parity oracle: our loader must accept
them and produce the same problems.  Those tests skip cleanly when the
reference checkout is absent, keeping the suite self-contained.
"""

import os

import pytest

from fixtures_paths import (
    REF_INSTANCES,
    local,
    local_instances,
    ref_instances,
    requires_reference,
)

from pydcop_tpu.dcop.objects import VariableNoisyCostFunc, VariableWithCostFunc
from pydcop_tpu.dcop.yamldcop import (
    dcop_yaml,
    load_dcop,
    load_dcop_from_file,
    load_dist,
    load_scenario,
    yaml_dist,
    yaml_scenario,
)

def test_minimal():
    dcop = load_dcop(
        """
name: test
objective: min
domains:
  d1:
    values: [0, 1, 2]
variables:
  v1:
    domain: d1
constraints:
  c1:
    type: intention
    function: v1 * 2
"""
    )
    assert dcop.name == "test"
    assert list(dcop.domains["d1"].values) == [0, 1, 2]
    assert dcop.constraint("c1")(v1=2) == 4


def test_range_domain():
    dcop = load_dcop(
        """
name: test
objective: min
domains:
  d1:
    values: [1 .. 5]
variables:
  v1: {domain: d1}
"""
    )
    assert list(dcop.domains["d1"].values) == [1, 2, 3, 4, 5]


def test_bool_domain():
    dcop = load_dcop(
        """
name: test
objective: min
domains:
  d1:
    values: [true, false]
variables:
  v1: {domain: d1}
"""
    )
    assert list(dcop.domains["d1"].values) == [True, False]


def test_variable_cost_and_noise():
    dcop = load_dcop(
        """
name: test
objective: min
domains:
  d1: {values: [0, 1, 2]}
variables:
  v1:
    domain: d1
    cost_function: v1 * 0.5
  v2:
    domain: d1
    cost_function: v2 * 2
    noise_level: 0.1
"""
    )
    v1, v2 = dcop.variable("v1"), dcop.variable("v2")
    assert isinstance(v1, VariableWithCostFunc)
    assert v1.cost_for_val(2) == 1.0
    assert isinstance(v2, VariableNoisyCostFunc)
    assert 2.0 <= v2.cost_for_val(1) < 2.1


def test_multiline_function_constraint():
    dcop = load_dcop(
        """
name: test
objective: min
domains:
  d1: {values: [0, 1, 2]}
variables:
  v1: {domain: d1}
constraints:
  c1:
    type: intention
    function: |
      if v1 == 2:
          return 10
      return v1
"""
    )
    c = dcop.constraint("c1")
    assert c(v1=2) == 10
    assert c(v1=1) == 1


def test_extensional_constraint():
    dcop = load_dcop(
        """
name: test
objective: min
domains:
  d1: {values: [1, 2, 3]}
variables:
  v1: {domain: d1}
  v2: {domain: d1}
constraints:
  c1:
    type: extensional
    default: 100
    variables: [v1, v2]
    values:
      10: 1 2 | 2 1
      0: 3 3
"""
    )
    c = dcop.constraint("c1")
    assert c(v1=1, v2=2) == 10
    assert c(v1=2, v2=1) == 10
    assert c(v1=3, v2=3) == 0
    assert c(v1=1, v2=1) == 100


def test_external_variable_and_partial():
    dcop = load_dcop(
        """
name: test
objective: min
domains:
  d1: {values: [0, 1, 2]}
  dbool: {values: [true, false]}
variables:
  v1: {domain: d1}
  v2: {domain: d1}
external_variables:
  e1:
    domain: dbool
    initial_value: true
constraints:
  c1:
    type: intention
    function: v1 if e1 else 2
  c2:
    type: intention
    function: v1 * 10 + v2
    partial:
      v2: 1
"""
    )
    assert dcop.get_external_variable("e1").value is True
    c2 = dcop.constraint("c2")
    assert c2.scope_names == ["v1"]
    assert c2(v1=2) == 21


def test_agents_routes_hosting():
    dcop = load_dcop(
        """
name: test
objective: min
domains:
  d1: {values: [0, 1]}
variables:
  v1: {domain: d1}
agents:
  a1: {capacity: 100}
  a2: {capacity: 50}
  a3: {}
routes:
  default: 5
  a1: {a2: 10}
hosting_costs:
  default: 1000
  a1:
    default: 7
    computations: {v1: 3}
"""
    )
    a1, a2, a3 = (dcop.agent(n) for n in ("a1", "a2", "a3"))
    assert a2.capacity == 50
    assert a1.route("a2") == 10
    assert a2.route("a1") == 10  # symmetric
    assert a1.route("a3") == 5
    assert a1.hosting_cost("v1") == 3
    assert a1.hosting_cost("other") == 7
    assert a3.hosting_cost("v1") == 1000


def test_duplicate_route_raises():
    from pydcop_tpu.dcop.yamldcop import DcopInvalidFormatError

    with pytest.raises(DcopInvalidFormatError):
        load_dcop(
            """
name: test
domains: {d1: {values: [0]}}
variables: {v1: {domain: d1}}
agents: [a1, a2]
routes:
  a1: {a2: 10}
  a2: {a1: 6}
"""
        )


def test_agents_as_list():
    dcop = load_dcop(
        """
name: test
domains: {d1: {values: [0]}}
variables: {v1: {domain: d1}}
agents: [a1, a2]
"""
    )
    assert set(dcop.agents) == {"a1", "a2"}


@pytest.mark.parametrize(
    "path",
    local_instances(),
    ids=[os.path.basename(p) for p in local_instances()],
)
def test_load_local_fixture(path):
    """Every committed local instance must load without error."""
    dcop = load_dcop_from_file(path)
    assert dcop.name
    assert dcop.variables


@requires_reference
@pytest.mark.parametrize(
    "fixture",
    sorted(os.path.basename(p) for p in ref_instances()),
)
def test_load_reference_fixture(fixture):
    """Parity tier: every reference fixture must load without error."""
    dcop = load_dcop_from_file(os.path.join(REF_INSTANCES, fixture))
    assert dcop.name
    assert dcop.variables


def test_local_coloring_semantics():
    dcop = load_dcop_from_file(local("coloring_chain.yaml"))
    assert dcop.objective == "min"
    c = dcop.constraint("clash_12")
    assert c(w1="B", w2="B") == 3
    assert c(w1="B", w2="Y") == 0
    assert dcop.variable("w1").cost_for_val("B") == -0.2
    cost, violations = dcop.solution_cost(
        {"w1": "B", "w2": "B", "w3": "P", "w4": "B"})
    # clash_12 (3) + prefs: -0.2 (w1=B) + 0.1 (w2=B) + 0.0 + -0.2
    assert abs(cost - 2.7) < 1e-9
    assert violations == 0
    assert dcop.dist_hints.must_host("b1") == ["w1"]


@requires_reference
def test_reference_graph_coloring_semantics():
    dcop = load_dcop_from_file(
        os.path.join(REF_INSTANCES, "graph_coloring1.yaml"))
    assert dcop.objective == "min"
    c = dcop.constraint("diff_1_2")
    assert c(v1="R", v2="R") == 1
    assert c(v1="R", v2="G") == 0
    assert dcop.variable("v1").cost_for_val("R") == -0.1
    cost, violations = dcop.solution_cost({"v1": "R", "v2": "G", "v3": "G"})
    assert abs(cost - 0.7) < 1e-9
    assert violations == 0
    assert dcop.dist_hints.must_host("a1") == ["v1"]


def test_external_python_constraint_fixture():
    dcop = load_dcop_from_file(local("coloring_chain_func.yaml"))
    assert dcop.constraint("clash_23")(w2="B", w3="B") == 3
    assert dcop.constraint("clash_23")(w2="B", w3="Y") == 0


def test_roundtrip_through_dump():
    src = load_dcop_from_file(local("coloring_chain.yaml"))
    dumped = dcop_yaml(src)
    again = load_dcop(dumped)
    assert set(again.variables) == set(src.variables)
    assert set(again.constraints) == set(src.constraints)
    asst = {"w1": "B", "w2": "Y", "w3": "P", "w4": "B"}
    assert again.solution_cost(asst) == src.solution_cost(asst)


def test_scenario_roundtrip():
    s = load_scenario(
        """
events:
  - id: w
    delay: 1
  - id: e1
    actions:
      - type: remove_agent
        agent: a2
"""
    )
    assert len(s) == 2
    assert s.events[0].is_delay
    assert s.events[1].actions[0].type == "remove_agent"
    assert s.events[1].actions[0].args == {"agent": "a2"}
    s2 = load_scenario(yaml_scenario(s))
    assert s2.events == s.events


def test_distribution_roundtrip():
    d = load_dist(
        """
distribution:
  a0: []
  a1: [v1, v2]
"""
    )
    assert d.computations_hosted("a1") == ["v1", "v2"]
    assert d.agent_for("v1") == "a1"
    d2 = load_dist(yaml_dist(d))
    assert d2 == d

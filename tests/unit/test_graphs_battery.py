"""Battery over utils/graphs.py — adjacency, components, diameters,
cycle counts, networkx bridges (reference test_graphs.py depth)."""

from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation, NeutralRelation
from pydcop_tpu.utils.graphs import (
    all_pairs,
    as_networkx_bipartite_graph,
    as_networkx_graph,
    calc_diameter,
    components,
    constraint_adjacency,
    cycles_count,
    graph_diameter,
)

d2 = Domain("d", "", [0, 1])


def vs(*names):
    return [Variable(n, d2) for n in names]


def binary(a, b, name="c"):
    return NAryMatrixRelation([a, b], name=name)


class TestAdjacency:
    def test_binary_constraints(self):
        a, b, c = vs("a", "b", "c")
        adj = constraint_adjacency([a, b, c], [binary(a, b)])
        assert adj["a"] == {"b"}
        assert adj["b"] == {"a"}
        assert adj["c"] == set()

    def test_ternary_constraint_forms_clique(self):
        a, b, c = vs("a", "b", "c")
        r = NeutralRelation([a, b, c], "t")
        adj = constraint_adjacency([a, b, c], [r])
        assert adj["a"] == {"b", "c"}
        assert adj["b"] == {"a", "c"}
        assert adj["c"] == {"a", "b"}

    def test_isolated_variables_present(self):
        a, b = vs("a", "b")
        adj = constraint_adjacency([a, b], [])
        assert adj == {"a": set(), "b": set()}


class TestComponents:
    def test_single_component(self):
        adj = {"a": {"b"}, "b": {"a", "c"}, "c": {"b"}}
        comps = components(adj)
        assert comps == [{"a", "b", "c"}]

    def test_two_components(self):
        adj = {"a": {"b"}, "b": {"a"}, "x": {"y"}, "y": {"x"}}
        comps = components(adj)
        assert {frozenset(c) for c in comps} == {
            frozenset({"a", "b"}), frozenset({"x", "y"})}

    def test_isolated_nodes_are_components(self):
        comps = components({"a": set(), "b": set()})
        assert len(comps) == 2


class TestDiameter:
    CHAIN = {"a": {"b"}, "b": {"a", "c"}, "c": {"b", "d"}, "d": {"c"}}

    def test_exact_chain(self):
        assert calc_diameter(self.CHAIN, exact=True) == 3

    def test_double_sweep_exact_on_trees(self):
        assert calc_diameter(self.CHAIN, exact=False) == 3

    def test_single_node(self):
        assert calc_diameter({"a": set()}) == 0

    def test_empty(self):
        assert calc_diameter({}) == 0

    def test_cycle_diameter(self):
        ring = {
            "a": {"b", "d"}, "b": {"a", "c"},
            "c": {"b", "d"}, "d": {"c", "a"},
        }
        assert calc_diameter(ring, exact=True) == 2

    def test_graph_diameter_per_component(self):
        a, b, c, x = vs("a", "b", "c", "x")
        cons = [binary(a, b, "c1"), binary(b, c, "c2")]
        diameters = graph_diameter([a, b, c, x], cons)
        assert sorted(diameters) == [0, 2]


class TestCycles:
    def test_tree_has_no_cycles(self):
        a, b, c = vs("a", "b", "c")
        cons = [binary(a, b, "c1"), binary(b, c, "c2")]
        assert cycles_count([a, b, c], cons) == 0

    def test_triangle_has_one(self):
        a, b, c = vs("a", "b", "c")
        cons = [binary(a, b, "c1"), binary(b, c, "c2"),
                binary(a, c, "c3")]
        assert cycles_count([a, b, c], cons) == 1

    def test_two_triangles(self):
        a, b, c, d = vs("a", "b", "c", "d")
        cons = [binary(a, b, "c1"), binary(b, c, "c2"),
                binary(a, c, "c3"), binary(b, d, "c4"),
                binary(c, d, "c5")]
        assert cycles_count([a, b, c, d], cons) == 2

    def test_disconnected_components_independent(self):
        a, b, x, y = vs("a", "b", "x", "y")
        cons = [binary(a, b, "c1"), binary(x, y, "c2")]
        assert cycles_count([a, b, x, y], cons) == 0


class TestHelpers:
    def test_all_pairs(self):
        assert list(all_pairs([1, 2, 3])) == [(1, 2), (1, 3), (2, 3)]
        assert list(all_pairs([1])) == []

    def test_networkx_graph(self):
        a, b, c = vs("a", "b", "c")
        g = as_networkx_graph([a, b, c], [binary(a, b)])
        assert set(g.nodes) == {"a", "b", "c"}
        assert g.has_edge("a", "b") and not g.has_edge("a", "c")

    def test_networkx_bipartite(self):
        a, b = vs("a", "b")
        r = binary(a, b, "c1")
        g = as_networkx_bipartite_graph([a, b], [r])
        assert set(g.nodes) == {"a", "b", "c1"}
        assert g.has_edge("a", "c1") and g.has_edge("b", "c1")
        assert not g.has_edge("a", "b")

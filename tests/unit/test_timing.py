"""engine/timing.py — honest device timing under an async dispatch
layer that may not implement block_until_ready faithfully (the axon
TPU tunnel; see the module docstring for the measured evidence)."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pydcop_tpu.engine.timing import (
    marginal_seconds_per_cycle,
    sync,
    timed_call,
    warmed_marginal,
)


class TestSync:
    def test_fetches_smallest_leaf_to_host(self, monkeypatch):
        """sync must force a REAL host fetch (device_get), and of the
        cheapest leaf: the scalar, not the big array — the fetch is
        the barrier, its size is the overhead."""
        import pydcop_tpu.engine.timing as timing_mod

        fetched = []
        real_device_get = jax.device_get

        def spy(x):
            fetched.append(getattr(x, "size", None))
            return real_device_get(x)

        monkeypatch.setattr(timing_mod.jax, "device_get", spy)
        big = jnp.ones((64, 64))
        small = jnp.int32(7)
        out = sync((big, small))
        assert out == (big, small)
        assert fetched == [1], (
            "sync must fetch exactly one leaf, the smallest")

    def test_no_fetch_without_array_leaves(self, monkeypatch):
        import pydcop_tpu.engine.timing as timing_mod

        fetched = []
        monkeypatch.setattr(
            timing_mod.jax, "device_get",
            lambda x: fetched.append(x))
        assert sync((1, "x", None)) == (1, "x", None)
        assert fetched == []

    def test_returns_pytree_unchanged(self):
        out = {"a": jnp.arange(4), "b": (jnp.float32(1.5),)}
        got = sync(out)
        assert got is out

    def test_handles_non_array_leaves(self):
        out = (jnp.arange(3), 7, "label", None)
        assert sync(out) is out

    def test_handles_empty_and_no_array_trees(self):
        assert sync({}) == {}
        assert sync((1, "x")) == (1, "x")

    def test_forces_materialization(self):
        # The smallest leaf is fetched; after sync the value must be
        # host-readable and correct.
        out = sync((jnp.arange(100), jnp.int32(42)))
        assert int(out[1]) == 42


class TestTimedCall:
    def test_out_and_positive_elapsed(self):
        fn = jax.jit(lambda x: (x * 2, jnp.sum(x)))
        x = jnp.arange(8.0)
        out, elapsed = timed_call(fn, x)
        assert elapsed > 0
        np.testing.assert_allclose(np.asarray(out[0]),
                                   np.arange(8.0) * 2)


class TestMarginalSecondsPerCycle:
    def test_recovers_slope_and_fixed(self):
        # Simulated device: fixed dispatch latency + linear per-cycle
        # cost, the regime the differencing exists for.
        per, fixed = 0.002, 0.005

        def run_cycles(n):
            time.sleep(fixed + per * n)

        got_per, got_fixed = marginal_seconds_per_cycle(
            run_cycles, 10, 40, reps=3)
        assert got_per == pytest.approx(per, rel=0.5)
        assert got_fixed == pytest.approx(fixed, abs=0.02)

    @pytest.mark.parametrize("fixed", [0.0, 0.004, 0.02])
    def test_slope_invariant_to_injected_constant_offset(self, fixed):
        """The whole point of the two-point differencing: a constant
        per-call offset (tunnel round-trip, enqueue) of ANY size must
        not move the recovered per-cycle rate."""
        per = 0.001

        got_per, got_fixed = marginal_seconds_per_cycle(
            lambda n: time.sleep(fixed + per * n), 5, 45, reps=3)
        assert got_per == pytest.approx(per, rel=0.5)
        # And the offset itself lands in the fixed term, not the rate.
        assert got_fixed == pytest.approx(fixed, abs=0.02)

    def test_noise_floored_at_zero(self):
        # A program faster than timer noise must clamp to 0, never a
        # negative rate.
        got_per, got_fixed = marginal_seconds_per_cycle(
            lambda n: None, 1, 2, reps=3)
        assert got_per >= 0.0
        assert got_fixed >= 0.0

    def test_rejects_bad_bounds(self):
        with pytest.raises(ValueError, match="hi > lo"):
            marginal_seconds_per_cycle(lambda n: None, 5, 5)

    def test_warmed_marginal_builds_once_and_returns_hi_output(self):
        calls = []

        def make_fn(n):
            calls.append(n)
            return lambda x: (x, jnp.int32(n))

        x = jnp.arange(4.0)
        per, fixed, out = warmed_marginal(make_fn, 3, 9, args=(x,),
                                          reps=2)
        # One build per cycle count, never per rep.
        assert sorted(calls) == [3, 9]
        # The third element is the warm full-length output — callers
        # reuse it instead of re-running the program.
        assert int(out[1]) == 9
        assert per >= 0.0 and fixed >= 0.0

    def test_real_jit_program_scales(self):
        # End-to-end on the test backend (CPU): a kernel whose work
        # scales with the cycle count must report a positive slope.
        def make(n):
            def body(i, a):
                return jnp.sin(a) + 1e-6 * i
            return jax.jit(
                lambda x: jax.lax.fori_loop(0, n, body, x))

        x = jnp.ones((512, 512), jnp.float32)
        fns = {n: make(n) for n in (2, 80)}
        for f in fns.values():
            sync(f(x))
        per, _ = marginal_seconds_per_cycle(
            lambda n: fns[n](x), 2, 80, reps=3)
        assert per > 0

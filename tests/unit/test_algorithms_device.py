"""Device/engine algorithm tests.

Exact algorithms (dpop, syncbb) are checked against brute-force optima
on random problems; local search (dsa, mgm) against quality invariants
(mgm monotonicity is structural: never worse than random init).
"""

import itertools

import numpy as np
import pytest

from pydcop_tpu.api import solve
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable, VariableWithCostDict
from pydcop_tpu.dcop.relations import NAryMatrixRelation, constraint_from_str


def brute_force(dcop):
    best, best_asst = np.inf, None
    names = list(dcop.variables)
    domains = [list(dcop.variables[n].domain) for n in names]
    sign = 1 if dcop.objective == "min" else -1
    for combo in itertools.product(*domains):
        asst = dict(zip(names, combo))
        cost, _ = dcop.solution_cost(asst)
        if sign * cost < best:
            best, best_asst = sign * cost, asst
    return sign * best, best_asst


def random_dcop(n_vars=8, n_constraints=12, d=3, seed=0, objective="min",
                with_var_costs=False, arity3=False):
    rng = np.random.default_rng(seed)
    dom = Domain("d", "", list(range(d)))
    dcop = DCOP("rand", objective=objective)
    variables = []
    for i in range(n_vars):
        if with_var_costs:
            costs = {v: float(rng.random()) for v in dom}
            variables.append(
                VariableWithCostDict(f"v{i}", dom, costs))
        else:
            variables.append(Variable(f"v{i}", dom))
    for k in range(n_constraints):
        arity = 3 if (arity3 and k % 4 == 0) else 2
        idx = rng.choice(n_vars, size=arity, replace=False)
        table = rng.integers(0, 10, size=(d,) * arity).astype(float)
        dcop.add_constraint(NAryMatrixRelation(
            [variables[i] for i in idx], table, f"c{k}"))
    return dcop


class TestDpop:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_optimal_vs_bruteforce(self, seed):
        dcop = random_dcop(seed=seed)
        expected_cost, _ = brute_force(dcop)
        res = solve(dcop, "dpop")
        assert res["cost"] == pytest.approx(expected_cost)

    def test_optimal_with_var_costs(self):
        dcop = random_dcop(seed=3, with_var_costs=True)
        expected_cost, _ = brute_force(dcop)
        res = solve(dcop, "dpop")
        assert res["cost"] == pytest.approx(expected_cost)

    def test_optimal_arity3(self):
        dcop = random_dcop(seed=4, arity3=True)
        expected_cost, _ = brute_force(dcop)
        res = solve(dcop, "dpop")
        assert res["cost"] == pytest.approx(expected_cost)

    def test_max_mode(self):
        dcop = random_dcop(seed=5, objective="max")
        expected_cost, _ = brute_force(dcop)
        res = solve(dcop, "dpop")
        assert res["cost"] == pytest.approx(expected_cost)

    def test_disconnected_components(self):
        dom = Domain("d", "", [0, 1])
        a, b, c, e = (Variable(n, dom) for n in "abce")
        dcop = DCOP("disc")
        dcop.add_constraint(constraint_from_str("c1", "a + b", [a, b]))
        dcop.add_constraint(constraint_from_str("c2", "2 - c - e", [c, e]))
        res = solve(dcop, "dpop")
        assert res["cost"] == 0
        assert res["assignment"] == {"a": 0, "b": 0, "c": 1, "e": 1}


class TestSyncBB:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_optimal_vs_bruteforce(self, seed):
        dcop = random_dcop(seed=seed)
        expected_cost, _ = brute_force(dcop)
        res = solve(dcop, "syncbb")
        assert res["cost"] == pytest.approx(expected_cost)

    def test_optimal_with_var_costs_and_arity3(self):
        dcop = random_dcop(seed=6, with_var_costs=True, arity3=True)
        expected_cost, _ = brute_force(dcop)
        res = solve(dcop, "syncbb")
        assert res["cost"] == pytest.approx(expected_cost)

    def test_max_mode(self):
        dcop = random_dcop(seed=7, objective="max")
        expected_cost, _ = brute_force(dcop)
        res = solve(dcop, "syncbb")
        assert res["cost"] == pytest.approx(expected_cost)

    def test_agrees_with_dpop(self):
        dcop = random_dcop(seed=8, n_vars=10, n_constraints=18)
        r1 = solve(dcop, "dpop")
        r2 = solve(dcop, "syncbb")
        assert r1["cost"] == pytest.approx(r2["cost"])


class TestNcbb:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_optimal_vs_bruteforce(self, seed):
        dcop = random_dcop(seed=seed)
        expected_cost, _ = brute_force(dcop)
        res = solve(dcop, "ncbb")
        assert res["cost"] == pytest.approx(expected_cost)

    def test_optimal_with_var_costs(self):
        dcop = random_dcop(seed=3, with_var_costs=True)
        expected_cost, _ = brute_force(dcop)
        res = solve(dcop, "ncbb")
        assert res["cost"] == pytest.approx(expected_cost)

    def test_max_mode(self):
        dcop = random_dcop(seed=4, objective="max")
        expected_cost, _ = brute_force(dcop)
        res = solve(dcop, "ncbb")
        assert res["cost"] == pytest.approx(expected_cost)

    def test_rejects_arity3(self):
        from pydcop_tpu.infrastructure.computations import (
            ComputationException,
        )

        dcop = random_dcop(seed=5, arity3=True)
        with pytest.raises(ComputationException):
            solve(dcop, "ncbb")

    def test_agrees_with_dpop(self):
        dcop = random_dcop(seed=11, n_vars=12, n_constraints=20)
        r1 = solve(dcop, "dpop")
        r2 = solve(dcop, "ncbb")
        assert r1["cost"] == pytest.approx(r2["cost"])

    def test_upper_bound_reported(self):
        dcop = random_dcop(seed=12)
        res = solve(dcop, "ncbb")
        # Greedy INIT bound is never better than the optimum.
        assert res["metrics"]["upper_bound"] >= res["cost"] - 1e-9


class TestLocalSearch:
    def test_dsa_reaches_reasonable_quality(self):
        dcop = random_dcop(seed=9, n_vars=20, n_constraints=30)
        optimal, _ = brute_force_sample(dcop)
        res = solve(dcop, "dsa", max_cycles=100)
        assert res["violations"] == 0
        # Local search should land within 2x of a sampled-good cost.
        assert res["cost"] <= optimal * 2 + 10

    def test_dsa_variants_and_params(self):
        dcop = random_dcop(seed=10)
        for variant in ("A", "B", "C"):
            res = solve(dcop, "dsa", max_cycles=30,
                        algo_params={"variant": variant})
            assert res["assignment"]
        res = solve(dcop, "dsa", max_cycles=30,
                    algo_params={"p_mode": "arity"})
        assert res["assignment"]

    def test_dsa_deterministic_given_seed(self):
        dcop = random_dcop(seed=11)
        r1 = solve(dcop, "dsa", max_cycles=40, algo_params={"seed": 5})
        r2 = solve(dcop, "dsa", max_cycles=40, algo_params={"seed": 5})
        assert r1["assignment"] == r2["assignment"]

    def test_mgm_monotone_quality(self):
        dcop = random_dcop(seed=12, n_vars=15, n_constraints=25)
        r_short = solve(dcop, "mgm", max_cycles=5)
        r_long = solve(dcop, "mgm", max_cycles=60)
        assert r_long["cost"] <= r_short["cost"] + 1e-6

    def test_mgm_break_modes(self):
        dcop = random_dcop(seed=13)
        for mode in ("lexic", "random"):
            res = solve(dcop, "mgm", max_cycles=30,
                        algo_params={"break_mode": mode})
            assert res["assignment"]

    def test_device_cost_matches_host_cost(self):
        """The on-device cost accumulator must agree with the host
        solution_cost evaluation (cross-validates the compiled arrays)."""
        dcop = random_dcop(seed=14, arity3=True, with_var_costs=True)
        for algo in ("dsa", "mgm"):
            res = solve(dcop, algo, max_cycles=30)
            assert res["metrics"]["device_cost"] == pytest.approx(
                res["cost"], rel=1e-5
            )


def brute_force_sample(dcop, n=2000, seed=0):
    """Sampled best cost (cheap stand-in for brute force on larger
    problems)."""
    rng = np.random.default_rng(seed)
    names = list(dcop.variables)
    domains = [list(dcop.variables[v].domain) for v in names]
    best, best_asst = np.inf, None
    for _ in range(n):
        asst = {
            v: d[rng.integers(len(d))] for v, d in zip(names, domains)
        }
        cost, _ = dcop.solution_cost(asst)
        if cost < best:
            best, best_asst = cost, asst
    return best, best_asst


def coloring_csp(n_vars=10, d=3, infinity=10000.0, seed=0,
                 extra_soft=False):
    """Ring + random chords graph coloring: equal colors cost
    `infinity`, else 0 (a DBA-style CSP; 3-colorable for sparse rings).
    With extra_soft, adds small random soft preferences."""
    rng = np.random.default_rng(seed)
    dom = Domain("colors", "color", list(range(d)))
    variables = [Variable(f"v{i}", dom) for i in range(n_vars)]
    dcop = DCOP("csp", objective="min")
    eq = np.where(np.eye(d) > 0, infinity, 0.0)
    edges = [(i, (i + 1) % n_vars) for i in range(n_vars)]
    for k in range(n_vars // 3):
        i, j = rng.choice(n_vars, size=2, replace=False)
        if (i, j) not in edges and (j, i) not in edges:
            edges.append((i, j))
    for k, (i, j) in enumerate(edges):
        dcop.add_constraint(NAryMatrixRelation(
            [variables[i], variables[j]], eq, f"c{k}"))
    if extra_soft:
        for k, (i, j) in enumerate(edges[: n_vars // 2]):
            table = rng.random((d, d))
            dcop.add_constraint(NAryMatrixRelation(
                [variables[i], variables[j]], table, f"s{k}"))
    return dcop


class TestDba:
    def test_solves_colorable_csp(self):
        dcop = coloring_csp(n_vars=12, d=3, seed=0)
        res = solve(dcop, "dba", max_cycles=200)
        # All constraints satisfied: no pair at cost 10000.
        assert res["cost"] == 0

    def test_breakout_escapes_local_minima(self):
        # Denser problem where plain best-response can get stuck.
        dcop = coloring_csp(n_vars=20, d=3, seed=1)
        res = solve(dcop, "dba", max_cycles=400,
                    algo_params={"seed": 3})
        assert res["cost"] == 0

    def test_early_termination(self):
        dcop = coloring_csp(n_vars=8, d=3, seed=2)
        res = solve(dcop, "dba", max_cycles=1000,
                    algo_params={"max_distance": 8})
        # Stops via the termination counter well before max_cycles.
        assert res["cycles"] < 1000
        assert res["cost"] == 0

    def test_rejects_max_mode(self):
        dcop = random_dcop(seed=3, objective="max")
        with pytest.raises(ValueError):
            solve(dcop, "dba", max_cycles=10)

    def test_deterministic_given_seed(self):
        dcop = coloring_csp(n_vars=10, seed=4)
        r1 = solve(dcop, "dba", max_cycles=50, algo_params={"seed": 7})
        r2 = solve(dcop, "dba", max_cycles=50, algo_params={"seed": 7})
        assert r1["assignment"] == r2["assignment"]

    def test_isolated_variable_does_not_abort_run(self):
        # Regression: an unconstrained variable's termination counter
        # must not stop components that still have violations.
        dcop = coloring_csp(n_vars=20, d=3, seed=5)
        dom = Domain("d", "", [0, 1, 2])
        dcop.add_variable(Variable("lonely", dom))
        res = solve(dcop, "dba", max_cycles=400,
                    algo_params={"max_distance": 10})
        assert res["cost"] == 0


class TestGdba:
    def test_reaches_reasonable_quality(self):
        dcop = random_dcop(seed=20, n_vars=15, n_constraints=25)
        sampled, _ = brute_force_sample(dcop)
        res = solve(dcop, "gdba", max_cycles=100)
        assert res["violations"] == 0
        assert res["cost"] <= sampled * 2 + 10

    @pytest.mark.parametrize("modifier", ["A", "M"])
    @pytest.mark.parametrize("violation", ["NZ", "NM", "MX"])
    def test_modifier_violation_modes(self, modifier, violation):
        dcop = random_dcop(seed=21, n_vars=8, n_constraints=12)
        res = solve(dcop, "gdba", max_cycles=30, algo_params={
            "modifier": modifier, "violation": violation})
        assert res["assignment"]

    @pytest.mark.parametrize("mode", ["E", "R", "C", "T"])
    def test_increase_modes(self, mode):
        dcop = random_dcop(seed=22, n_vars=8, n_constraints=12)
        res = solve(dcop, "gdba", max_cycles=30,
                    algo_params={"increase_mode": mode})
        assert res["assignment"]

    def test_arity3(self):
        dcop = random_dcop(seed=23, arity3=True)
        res = solve(dcop, "gdba", max_cycles=30)
        assert res["assignment"]

    def test_cost_reported_on_base_costs(self):
        dcop = random_dcop(seed=24)
        res = solve(dcop, "gdba", max_cycles=50)
        assert res["metrics"]["device_cost"] == pytest.approx(
            res["cost"], rel=1e-5)


class TestMixedDsa:
    def test_satisfies_hard_and_optimizes_soft(self):
        dcop = coloring_csp(n_vars=12, d=3, seed=30,
                            infinity=float("inf"), extra_soft=True)
        res = solve(dcop, "mixeddsa", max_cycles=200)
        assert res["violations"] == 0

    @pytest.mark.parametrize("variant", ["A", "B", "C"])
    def test_variants(self, variant):
        dcop = coloring_csp(n_vars=10, d=3, seed=31,
                            infinity=float("inf"), extra_soft=True)
        res = solve(dcop, "mixeddsa", max_cycles=100,
                    algo_params={"variant": variant})
        assert res["assignment"]

    def test_soft_only_behaves_like_dsa(self):
        dcop = random_dcop(seed=32, n_vars=15, n_constraints=25)
        sampled, _ = brute_force_sample(dcop)
        res = solve(dcop, "mixeddsa", max_cycles=100)
        assert res["cost"] <= sampled * 2 + 10

    def test_deterministic_given_seed(self):
        dcop = coloring_csp(n_vars=10, seed=33, infinity=float("inf"))
        r1 = solve(dcop, "mixeddsa", max_cycles=40,
                   algo_params={"seed": 9})
        r2 = solve(dcop, "mixeddsa", max_cycles=40,
                   algo_params={"seed": 9})
        assert r1["assignment"] == r2["assignment"]


class TestMgm2:
    def test_reaches_reasonable_quality(self):
        dcop = random_dcop(seed=40, n_vars=15, n_constraints=25)
        sampled, _ = brute_force_sample(dcop)
        res = solve(dcop, "mgm2", max_cycles=100)
        assert res["violations"] == 0
        assert res["cost"] <= sampled * 2 + 10

    def test_beats_or_matches_mgm_on_average(self):
        # 2-opt moves escape 1-opt local minima; over a few seeds MGM2
        # should never be much worse than MGM.
        deltas = []
        for seed in (41, 42, 43):
            dcop = random_dcop(seed=seed, n_vars=12, n_constraints=24)
            r2 = solve(dcop, "mgm2", max_cycles=80,
                       algo_params={"threshold": 0.5})
            r1 = solve(dcop, "mgm", max_cycles=80)
            deltas.append(r2["cost"] - r1["cost"])
        assert np.mean(deltas) <= 2.0

    @pytest.mark.parametrize("favor", ["unilateral", "no", "coordinated"])
    def test_favor_modes(self, favor):
        dcop = random_dcop(seed=44)
        res = solve(dcop, "mgm2", max_cycles=40,
                    algo_params={"favor": favor})
        assert res["assignment"]

    def test_threshold_extremes(self):
        dcop = random_dcop(seed=45)
        # threshold 0: nobody offers -> pure MGM behavior; 1: everyone
        # offers (and everyone being an offerer, nobody accepts).
        for th in (0.0, 1.0):
            res = solve(dcop, "mgm2", max_cycles=40,
                        algo_params={"threshold": th})
            assert res["assignment"]

    def test_arity3(self):
        dcop = random_dcop(seed=46, arity3=True)
        res = solve(dcop, "mgm2", max_cycles=40)
        assert res["assignment"]

    def test_deterministic_given_seed(self):
        dcop = random_dcop(seed=47)
        r1 = solve(dcop, "mgm2", max_cycles=40, algo_params={"seed": 3})
        r2 = solve(dcop, "mgm2", max_cycles=40, algo_params={"seed": 3})
        assert r1["assignment"] == r2["assignment"]

    def test_stop_cycle(self):
        dcop = random_dcop(seed=48)
        res = solve(dcop, "mgm2", algo_params={"stop_cycle": 7})
        assert res["cycles"] == 7

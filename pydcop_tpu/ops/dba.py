"""DBA (Distributed Breakout Algorithm) step kernel.

Reference parity: pydcop/algorithms/dba.py:272-595 (Yokoo & Hirayama
1996 semantics).  DBA is a constraint-*satisfaction* local search: the
objective is the weighted count of violated constraints (violated =
cost >= `infinity`), with per-(variable, constraint) breakout weights
that start at 1 and increase when a neighborhood is stuck in a
quasi-local minimum.

One lockstep cycle = the reference's ok-phase + improve-phase:

- each variable computes its weighted violation count for every
  candidate value, with neighbors fixed at previous-cycle values
  (compute_eval_value, dba.py:452), and its best improvement
  (_compute_best_improvement :424);
- improvements are exchanged; a variable moves iff its improvement is
  positive and strictly largest in its neighborhood, lexically-smallest
  name winning ties (dba.py:507-517);
- a neighborhood where nobody can improve is a quasi-local minimum: its
  variables increase their own weights of currently-violated constraints
  by 1 (breakout, dba.py:553-565);
- termination detection: each variable tracks a counter, reset when its
  own eval is non-zero (dba.py:405), set to the min of its neighbors'
  counters (:509), incremented while the whole neighborhood is
  consistent (:541); the run stops when any counter reaches
  `max_distance` (the reference then broadcasts DbaEndMessage, :545).
"""

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from pydcop_tpu.engine.compile import CompiledFactorGraph
from pydcop_tpu.ops.localsearch import (
    _fix_other_axes,
    factor_current_costs,
    neighbor_max,
    neighborhood_winners,
    positional_sum,
    random_initial_values,
)


class DbaState(NamedTuple):
    values: jnp.ndarray             # [V+1] int32
    weights: Tuple[jnp.ndarray, ...]  # per bucket [F, arity] f32
    term_counter: jnp.ndarray       # [V+1] f32
    key: jnp.ndarray
    cycle: jnp.ndarray


def init_state(graph: CompiledFactorGraph, seed: int = 0) -> DbaState:
    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    return DbaState(
        values=random_initial_values(k0, graph),
        weights=tuple(
            jnp.ones(b.var_ids.shape, dtype=jnp.float32)
            for b in graph.buckets
        ),
        term_counter=jnp.zeros(
            (graph.var_costs.shape[0],), dtype=jnp.float32
        ),
        key=key,
        cycle=jnp.asarray(0, dtype=jnp.int32),
    )


def _weighted_violation_counts(graph: CompiledFactorGraph,
                               weights: Tuple[jnp.ndarray, ...],
                               values: jnp.ndarray,
                               infinity: float) -> jnp.ndarray:
    """[V+1, D]: per variable and candidate value, the weighted count of
    incident violated constraints, neighbors at `values`
    (compute_eval_value, dba.py:452 — constraints only, no unary costs)."""
    per_bucket = []
    for bucket, w in zip(graph.buckets, weights):
        arity = bucket.var_ids.shape[1]
        cols = []
        for p in range(arity):
            fixed = _fix_other_axes(bucket.costs, bucket.var_ids, values, p)
            viol = (fixed >= infinity).astype(jnp.float32)
            cols.append(w[:, p:p + 1] * viol)
        per_bucket.append(jnp.stack(cols, axis=1))
    return positional_sum(
        graph, per_bucket, jnp.zeros_like(graph.var_costs))


def violation_count(graph: CompiledFactorGraph, values: jnp.ndarray,
                    infinity: float) -> jnp.ndarray:
    """Scalar unweighted count of violated constraints — DBA's solution
    quality measure (a consistent assignment has count 0)."""
    total = jnp.asarray(0.0, dtype=jnp.float32)
    for cur in factor_current_costs(graph, values):
        total = total + jnp.sum((cur >= infinity).astype(jnp.float32))
    return total


def dba_step(state: DbaState, graph: CompiledFactorGraph, *,
             infinity: float, lexic_ranks: jnp.ndarray) -> DbaState:
    """One lockstep DBA cycle (ok + improve phases)."""
    key, k_choice = jax.random.split(state.key)
    values = state.values

    cand = _weighted_violation_counts(
        graph, state.weights, values, infinity
    )
    cur_eval = jnp.take_along_axis(cand, values[:, None], axis=1).squeeze(1)
    improve, proposed, nmax, wins = neighborhood_winners(
        graph, cand, values, k_choice, lexic_ranks
    )
    can_move = (improve > 0) & wins
    # Quasi-local minimum: nobody in the neighborhood (self included)
    # can improve (dba.py:409-414, cleared at :514).
    qlm = (improve <= 0) & (nmax <= improve)

    # Consistency: own eval zero and every neighbor's eval zero
    # (dba.py:403-407 own, :518-519 via improve messages).
    n_eval_max = neighbor_max(graph, cur_eval)
    consistent = (cur_eval == 0) & (n_eval_max <= 0)

    # Termination counters (dba.py:405 reset, :509 neighbor-min, :541 inc).
    tc = jnp.where(cur_eval == 0, state.term_counter, 0.0)
    n_tc_min = -neighbor_max(graph, -tc)
    tc = jnp.minimum(tc, n_tc_min)
    tc = jnp.where(consistent, tc + 1.0, tc)

    # Breakout: QLM variables increase their weight of each incident
    # violated constraint by 1 (dba.py:563-565).
    cur_viol = tuple(
        (cur >= infinity) for cur in factor_current_costs(graph, values)
    )
    new_weights = []
    for bucket, w, viol in zip(graph.buckets, state.weights, cur_viol):
        arity = bucket.var_ids.shape[1]
        bumps = []
        for p in range(arity):
            bump = (qlm[bucket.var_ids[:, p]] & viol).astype(jnp.float32)
            bumps.append(bump)
        new_weights.append(w + jnp.stack(bumps, axis=1))

    values = jnp.where(can_move, proposed, values)
    return DbaState(
        values=values,
        weights=tuple(new_weights),
        term_counter=tc,
        key=key,
        cycle=state.cycle + 1,
    )


def run_dba(graph: CompiledFactorGraph, max_cycles: int, *,
            infinity: float = 10000.0, max_distance: int = 50,
            lexic_ranks: jnp.ndarray, seed: int = 0,
            ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Full DBA run in one XLA program.

    Returns (values [V], final unweighted violation count, cycles).
    Stops early when *every* variable's termination counter reaches
    `max_distance` — the lockstep analogue of the reference's run
    ending once all computations have finished (a DbaEndMessage only
    propagates within a connected component, dba.py:576-590, and the
    orchestrator waits for all of them); stopping on *any* counter
    would let an unconstrained variable or an early-satisfied component
    abort components that still have violations."""
    state = init_state(graph, seed)

    def cond(s: DbaState):
        return (s.cycle < max_cycles) & ~jnp.all(
            s.term_counter[:-1] >= max_distance
        )

    def body(s: DbaState):
        return dba_step(
            s, graph, infinity=infinity, lexic_ranks=lexic_ranks
        )

    state = jax.lax.while_loop(cond, body, state)
    cost = violation_count(graph, state.values, infinity)
    return state.values[:-1], cost, state.cycle

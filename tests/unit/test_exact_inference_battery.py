"""Battery for the exact-inference subsystem (ISSUE 17):

- exactness: DPOP assignments score exactly the SyncBB optimum on
  seeded trees and width-bounded cyclic graphs with integer tables;
- cross-edge consistency (CEC) preprocessing: CEC-on assignments are
  bit-identical to CEC-off on random structures in both objective
  modes, crafted dominated instances actually prune (and shrink the
  UTIL hypercubes tree_stats reports), and the ``cec=off`` algo
  param turns the pass off;
- pseudo-tree construction: deterministic across repeated builds,
  depth/level invariants hold, and the host-numpy engine fallback
  still engages below the device-amortization threshold;
- width-keyed portfolio routing: on the domino chain (a structure
  where every iterative candidate's 60-cycle race leg is far from
  the optimum) ``algo="auto"`` resolves to DPOP, the decision
  replays from the persisted cache with zero re-measurement, and an
  over-width structure keeps DPOP out of the race entirely;
- the serving tier: ``algo:"dpop"`` over real HTTP returns
  ``optimal: true`` with the same assignment as a solo exact solve,
  an over-width request is a structured 400 ``rejected_width`` (the
  admission breaker never sees it), and ``/stats`` counts the exact
  dispatches;
- the session oracle: a quiesced session is certified by a
  background exact solve (delta in ``/stats`` + the session SSE
  stream), and an IMPROVING certification replaces the served
  assignment without recompiling the warm engine.
"""

import itertools
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pydcop_tpu import api
from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.algorithms.dpop import solve_on_device
from pydcop_tpu.computations_graph import pseudotree as pt
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.ops import dpop as dpop_ops


def _random_dcop(n, d, seed, extra_edges=0, objective="min",
                 integer=True, lo=0, hi=20):
    """Random spanning tree + optional extra edges, integer tables by
    default (integer optima make cost equality exact, not approx)."""
    rng = np.random.default_rng(seed)
    dom = Domain("c", "", list(range(d)))
    dcop = DCOP("t", objective=objective)
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)

    def table(shape):
        if integer:
            return rng.integers(lo, hi, shape).astype(float)
        return rng.random(shape)

    k = 0
    for i in range(1, n):
        p = rng.integers(0, i)
        dcop.add_constraint(NAryMatrixRelation(
            [vs[p], vs[i]], table((d, d)), f"c{k}"))
        k += 1
    for _ in range(extra_edges):
        i, j = rng.choice(n, size=2, replace=False)
        dcop.add_constraint(NAryMatrixRelation(
            [vs[i], vs[j]], table((d, d)), f"c{k}"))
        k += 1
    dcop.add_agents([AgentDef(f"a{i}") for i in range(n)])
    return dcop


def _domino_chain(n=140, weak_at=None):
    """The portfolio battery structure: a binary agreement chain with
    one weak link in the middle and opposing biases pinned at the two
    ends.  The optimum (cost 1: break at the weak link) needs
    end-to-end propagation — more cycles than any iterative
    candidate's race leg gets — so DPOP is the only candidate whose
    race answer lands within cost tolerance of the best."""
    weak_at = n // 2 if weak_at is None else weak_at
    dom = Domain("b", "", [0, 1])
    dcop = DCOP("domino", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n - 1):
        m = (np.array([[0.0, 1.0], [1.0, 0.0]]) if i == weak_at
             else np.array([[0.0, 5.0], [5.0, 0.0]]))
        if i == 0:
            m = m + np.array([[0.0, 0.0], [3.0, 3.0]])   # v0 -> 0
        if i == n - 2:
            m = m + np.array([[3.0, 0.0], [3.0, 0.0]])   # v_last -> 1
        dcop.add_constraint(NAryMatrixRelation(
            [vs[i], vs[i + 1]], m, f"m{i}"))
    dcop.add_agents([AgentDef(f"a{i}") for i in range(n)])
    return dcop


def _dpop(dcop, engine="jit", cec="on"):
    algo = AlgorithmDef.build_with_default_param(
        "dpop", {"engine": engine, "cec": cec}, mode=dcop.objective)
    return solve_on_device(dcop, algo)


# ------------------------------------------------------------------ #
# exactness: DPOP == SyncBB optimum


class TestExactnessVsSyncBB:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_tree_optimum(self, seed):
        dcop = _random_dcop(10, 3, seed)
        exact = _dpop(dcop)
        ref = api.solve(dcop, "syncbb", backend="device")
        cost, violations = dcop.solution_cost(exact.assignment)
        assert violations == 0
        assert cost == ref.cost, \
            "DPOP must land exactly on the SyncBB optimum"
        assert exact.metrics["optimal"] is True

    @pytest.mark.parametrize("seed", [3, 4])
    def test_width_bounded_graph_optimum(self, seed):
        """Back edges widen separators: still exact, still optimal."""
        dcop = _random_dcop(9, 3, seed, extra_edges=4)
        exact = _dpop(dcop)
        ref = api.solve(dcop, "syncbb", backend="device")
        cost, _ = dcop.solution_cost(exact.assignment)
        assert cost == ref.cost

    def test_max_mode_optimum(self):
        dcop = _random_dcop(8, 3, 5, extra_edges=2, objective="max")
        exact = _dpop(dcop)
        ref = api.solve(dcop, "syncbb", backend="device")
        cost, _ = dcop.solution_cost(exact.assignment)
        assert cost == ref.cost


# ------------------------------------------------------------------ #
# CEC preprocessing


class TestCecConsistency:
    @pytest.mark.parametrize("objective", ["min", "max"])
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_bit_identical_assignments(self, seed, objective):
        dcop = _random_dcop(25, 4, seed, extra_edges=5,
                            objective=objective, integer=False)
        graph = pt.build_computation_graph(dcop)
        a_off, s_off = dpop_ops.solve_sweep(graph, mode=objective,
                                            cec=False)
        a_on, s_on = dpop_ops.solve_sweep(graph, mode=objective,
                                          cec=True)
        assert a_on == a_off, \
            "CEC must be a pure optimization: identical assignments"
        assert s_on["cec_pruned"] >= 0

    def test_dominated_values_are_pruned(self):
        """Crafted domination: half the domain carries a flat +10
        offset in its unary AND every binary row — soft dominance
        prunes those values and tree_stats shrinks."""
        rng = np.random.default_rng(7)
        d = 6
        dom = Domain("c", "", list(range(d)))
        dcop = DCOP("dom", objective="min")
        vs = [Variable(f"v{i}", dom) for i in range(8)]
        offset = np.zeros(d)
        offset[d // 2:] = 10.0
        for v in vs:
            dcop.add_variable(v)
        for i in range(1, 8):
            base = rng.random((d, d))
            m = base + offset[:, None] + offset[None, :]
            dcop.add_constraint(NAryMatrixRelation(
                [vs[i - 1], vs[i]], m, f"c{i}"))
        dcop.add_agents([AgentDef("a0")])
        graph = pt.build_computation_graph(dcop)
        survivors, meta = dpop_ops.cec_survivors(graph, "min")
        assert meta["pruned"] > 0, "domination must prune something"
        raw = dpop_ops.tree_stats(graph)
        shrunk = dpop_ops.tree_stats(graph, survivors)
        assert shrunk["max_elements"] < raw["max_elements"], \
            "pruned survivors must shrink the UTIL hypercubes"
        a_on, stats = dpop_ops.solve_sweep(graph, "min", cec=True)
        a_off, _ = dpop_ops.solve_sweep(graph, "min", cec=False)
        assert a_on == a_off
        assert stats["cec_pruned"] == meta["pruned"]

    def test_cec_off_param_disables_the_pass(self):
        dcop = _random_dcop(12, 3, 9)
        res = _dpop(dcop, cec="off")
        assert res.metrics.get("cec_pruned", 0) == 0
        on = _dpop(dcop, cec="on")
        assert on.assignment == res.assignment


# ------------------------------------------------------------------ #
# pseudo-tree construction


class TestPseudoTreeInvariants:
    def test_deterministic_across_builds(self):
        dcop = _random_dcop(30, 3, 11, extra_edges=6)

        def shape(graph):
            return sorted(
                (n.name, n.parent, tuple(sorted(n.pseudo_parents)),
                 tuple(sorted(n.children)))
                for n in graph.nodes)

        g1 = pt.build_computation_graph(dcop)
        g2 = pt.build_computation_graph(dcop)
        assert shape(g1) == shape(g2), \
            "pseudo-tree construction must be deterministic"
        s1 = dpop_ops.tree_stats(g1)
        s2 = dpop_ops.tree_stats(g2)
        assert s1 == s2

    def test_depth_and_level_invariants(self):
        dcop = _random_dcop(40, 3, 13, extra_edges=8)
        graph = pt.build_computation_graph(dcop)
        depths = pt.node_depths(graph)
        by_name = {n.name: n for n in graph.nodes}
        for name, node in by_name.items():
            if node.parent is None:
                assert depths[name] == 0
            else:
                assert depths[name] == depths[node.parent] + 1
            # Pseudo-parents are ancestors: strictly shallower.
            for pp in node.pseudo_parents:
                assert depths[pp] < depths[name]
        stats = dpop_ops.tree_stats(graph)
        assert stats["nodes"] == 40
        assert stats["levels"] == max(depths.values()) + 1
        assert 1 <= stats["induced_width"] <= 39

    def test_numpy_fallback_below_amortization_threshold(self):
        """Tiny problems never pay device dispatch: engine=auto routes
        them through the host-numpy sweep."""
        dcop = _random_dcop(6, 2, 17)
        res = _dpop(dcop, engine="auto")
        assert res.metrics["engine"] == "numpy"
        jit = _dpop(dcop, engine="jit")
        cost_np, _ = dcop.solution_cost(res.assignment)
        cost_jit, _ = dcop.solution_cost(jit.assignment)
        assert cost_np == cost_jit


# ------------------------------------------------------------------ #
# width-keyed portfolio routing


class TestPortfolioRouting:
    def test_auto_picks_dpop_on_domino_then_replays_cached(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("PYDCOP_AGG_AUTOTUNE_CACHE",
                           str(tmp_path / "autotune.json"))
        dcop = _domino_chain(140)
        res = api.solve(dcop, "auto", backend="device")
        info = res["metrics"]["portfolio"]
        assert info["algo"] == "dpop", \
            "only the exact candidate is cost-eligible on the domino"
        assert res.cost == 1.0, "auto must serve the true optimum"
        # Same structure again: the decision replays from the shape
        # cache — no re-measurement race.
        res2 = api.solve(_domino_chain(140), "auto", backend="device")
        info2 = res2["metrics"]["portfolio"]
        assert info2["portfolio_source"] == "cache"
        assert info2["algo"] == "dpop"
        assert res2.cost == 1.0

    def test_over_width_structure_races_without_dpop(
            self, tmp_path, monkeypatch):
        """Past the race's element gate the dpop runner declines:
        auto resolves to an iterative candidate instead of failing."""
        from pydcop_tpu.engine.autotune import (
            DPOP_RACE_MAX_ELEMENTS,
            dpop_portfolio_runner,
        )
        from pydcop_tpu.engine.compile import compile_dcop

        # A 10-variable clique over a 10-value domain: induced width
        # 9, UTIL hypercubes of 10^10 cells — far past the race gate.
        n, d = 10, 10
        dom = Domain("c", "", list(range(d)))
        dcop = DCOP("clique", objective="min")
        vs = [Variable(f"x{i}", dom) for i in range(n)]
        for v in vs:
            dcop.add_variable(v)
        rng = np.random.default_rng(3)
        k = 0
        for i in range(n):
            for j in range(i + 1, n):
                dcop.add_constraint(NAryMatrixRelation(
                    [vs[i], vs[j]], rng.random((d, d)), f"c{k}"))
                k += 1
        dcop.add_agents([AgentDef("a0")])
        ptree = pt.build_computation_graph(dcop)
        stats = dpop_ops.tree_stats(ptree)
        assert stats["max_elements"] > DPOP_RACE_MAX_ELEMENTS
        graph, meta = compile_dcop(dcop)
        assert dpop_portfolio_runner(dcop, graph, meta) is None, \
            "over-width structures must not enter the race"


# ------------------------------------------------------------------ #
# serving tier over real HTTP


def _post(url, body):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read())


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return json.loads(r.read())


def _wide_clique_yaml(n=12, d=10):
    lines = ["name: wide", "objective: min", "domains:",
             "  d: {values: [" + ", ".join(map(str, range(d))) + "]}",
             "variables:"]
    for i in range(n):
        lines.append(f"  x{i}: {{domain: d}}")
    lines.append("constraints:")
    for i, j in itertools.combinations(range(n), 2):
        lines.append(f"  c{i}_{j}: {{type: intention, function: "
                     f"\"1 if x{i} == x{j} else 0\"}}")
    lines.append("agents: [a0]")
    return "\n".join(lines)


class TestDpopServingHTTP:
    def test_dpop_request_is_optimal_and_matches_solo(self):
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        dcop = _random_dcop(10, 3, 21)
        yaml_src = dcop_yaml(dcop)
        with api.serve(port=0, batch_window_s=0.02) as handle:
            code, res = _post(handle.url + "/solve",
                              {"dcop": yaml_src, "wait": True,
                               "params": {"algo": "dpop"}})
            assert code == 200 and res["status"] == "FINISHED"
            assert res["optimal"] is True, \
                "exact dispatches must certify their result"
            solo = _dpop(dcop)
            assert {k: v for k, v in res["assignment"].items()} == \
                {k: v for k, v in solo.assignment.items()}
            stats = _get(handle.url + "/stats")
            assert stats["dpop_dispatches"] >= 1
            # The iterative default never carries the flag.
            code2, res2 = _post(handle.url + "/solve",
                                {"dcop": yaml_src, "wait": True})
            assert code2 == 200 and "optimal" not in res2

    def test_over_width_is_structured_400_not_breaker_500(self):
        with api.serve(port=0, batch_window_s=0.02,
                       breaker_failures=1) as handle:
            code, res = _post(handle.url + "/solve",
                              {"dcop": _wide_clique_yaml(),
                               "wait": True,
                               "params": {"algo": "dpop"}})
            assert code == 400, \
                "an over-width exact request is a client error"
            assert res["status"] == "rejected_width"
            assert res["max_elements"] > res["max_elements_cap"]
            assert res["retry"] is False
            # The breaker never saw it (breaker_failures=1 would have
            # opened on a single dispatch failure): healthy service,
            # iterative requests still served.
            stats = _get(handle.url + "/stats")
            assert stats["breaker_state"] == "closed"
            code2, res2 = _post(
                handle.url + "/solve",
                {"dcop": _wide_clique_yaml(), "wait": True,
                 "params": {"algo": "maxsum", "max_cycles": 20}})
            assert code2 == 200 and res2["status"] == "FINISHED"

    def test_unknown_algo_param_rejected(self):
        with api.serve(port=0, batch_window_s=0.02) as handle:
            code, res = _post(handle.url + "/solve",
                              {"dcop": _wide_clique_yaml(4, 2),
                               "wait": True,
                               "params": {"algo": "simplex"}})
            assert code == 400
            assert "algo" in res["error"]


# ------------------------------------------------------------------ #
# the session oracle


class TestSessionOracle:
    def _open(self, svc, dcop, params):
        return svc.sessions.open(dcop, params=params)

    def _wait_quiesced(self, svc, sid, timeout=30.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            st = svc.sessions.status(sid)
            last = st["last"]
            if last is not None and (last.get("converged")
                                     or st.get("budget", 1) == 0):
                return st
            time.sleep(0.05)
        raise AssertionError(f"session {sid} never quiesced")

    def _wait_certified(self, svc, n=1, timeout=30.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            stats = svc.sessions.stats()
            if stats["certifications"] >= n:
                return stats
            time.sleep(0.05)
        raise AssertionError("oracle never certified "
                             f"(stats: {svc.sessions.stats()})")

    def test_quiesced_session_is_certified_with_delta_in_stats(self):
        from pydcop_tpu.serving.service import SolveService

        svc = SolveService(batch_window_s=0.02,
                           session_certify_after=0.2).start()
        try:
            sess = self._open(svc, _random_dcop(10, 3, 31),
                              {"noise": 0.0, "max_cycles": 300})
            q = svc.sessions.subscribe(sess.id)
            self._wait_quiesced(svc, sess.id)
            stats = self._wait_certified(svc)
            cert = stats["last_certification"]
            assert cert["session"] == sess.id
            assert cert["delta"] >= 0.0
            assert stats["certify_after"] == pytest.approx(0.2)
            # The SSE stream carried the certified event.
            deadline = time.monotonic() + 10
            phases = []
            while time.monotonic() < deadline:
                try:
                    ev = q.get(timeout=0.5)
                except Exception:
                    continue
                phases.append(ev.get("phase"))
                if ev.get("phase") == "certified":
                    assert ev["optimal"] is True
                    assert ev["certified_cost"] == pytest.approx(
                        cert["certified_cost"])
                    assert "delta" in ev
                    break
            assert "certified" in phases, \
                f"no certified SSE event (saw {phases})"
        finally:
            svc.stop(drain=False)

    def test_improving_certification_updates_served_assignment(self):
        """On the domino chain the warm fixpoint is provably
        suboptimal within the cycle budget: the oracle's exact solve
        must IMPROVE the served answer in place — no recompile."""
        from pydcop_tpu.serving.service import SolveService

        svc = SolveService(batch_window_s=0.02,
                           session_certify_after=0.2).start()
        try:
            dcop = _domino_chain(60, weak_at=30)
            sess = self._open(svc, dcop, {
                "noise": 0.0, "max_cycles": 30,
                "segment_cycles": 15})
            # Certification only happens after quiescence — waiting
            # for it subsumes waiting for the fixpoint.
            stats = self._wait_certified(svc)
            cert = stats["last_certification"]
            assert cert["improved"] is True
            assert cert["delta"] > 0
            assert cert["certified_cost"] == pytest.approx(1.0)
            assert cert["fixpoint_cost"] > cert["certified_cost"]
            st1 = svc.sessions.status(sess.id)
            assert st1["last"]["cost"] == pytest.approx(1.0), \
                "the served answer must upgrade to the optimum"
            assert st1["last"]["optimal"] is True
            cost, violations = dcop.solution_cost(
                st1["last"]["assignment"])
            assert violations == 0 and cost == pytest.approx(1.0)
            assert st1["recompiles"] == 0, \
                "certification must never recompile the warm engine"
            assert stats["certified_improved"] >= 1
        finally:
            svc.stop(drain=False)

    def test_oracle_off_by_default(self):
        from pydcop_tpu.serving.service import SolveService

        svc = SolveService(batch_window_s=0.02).start()
        try:
            sess = self._open(svc, _random_dcop(8, 3, 37),
                              {"noise": 0.0, "max_cycles": 200})
            self._wait_quiesced(svc, sess.id)
            time.sleep(0.5)
            stats = svc.sessions.stats()
            assert stats["certify_after"] is None
            assert stats["certifications"] == 0
        finally:
            svc.stop(drain=False)

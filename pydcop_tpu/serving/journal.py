"""Durable request journal: crash recovery for the solve service.

A ``pydcop serve`` process crash loses every accepted request — the
client got its 202, the queue was in memory, the memory is gone.
This module makes the 202 a *durable* promise: every admitted request
is appended to an on-disk journal BEFORE the ack is returned, every
terminal outcome (finished / error / expired) is appended when it
happens, and a restart with ``--recover`` replays exactly the
accepted-but-unfinished entries through the normal queue.

On-disk format (one file, ``requests.jnl``, append-only):

- each record is ``[u32 length][u32 crc32][payload]`` (big-endian
  header, JSON payload) — the same verify-on-read discipline as the
  PR-4 checkpoint checksums: the write path is trusted for nothing;
- a torn tail (the process died mid-append, or the disk lied) is
  detected by the length/crc check and TRUNCATED past the last valid
  record on recovery — every record before it is intact by
  construction, so a crash can only ever cost the unacknowledged
  suffix;
- recovery then COMPACTS the journal: the surviving file holds only
  the still-pending accepted records, so journals don't grow without
  bound across restarts and a second crash replays the same pending
  set again.

Durability model: ``append`` flushes to the OS on every record, so a
process kill (SIGKILL, OOM, crash) loses nothing acknowledged;
``sync=True`` adds an fsync per record for machine-crash durability
at a per-request latency cost.

The service side lives in serving/service.py (``journal_dir=`` /
``recover=``); the wire side in serving/http.py; ``pydcop serve
--journal_dir D --recover`` is the operational entry point
(docs/serving.md, docs/resilience.md "Serving & sharding fault
tolerance").
"""

import binascii
import json
import logging
import os
import struct
import tempfile
import threading
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger("pydcop.serving.journal")

# Record header: payload byte length + crc32 of the payload.
_HEADER = struct.Struct(">II")
# Refuse absurd lengths on read: a corrupt header must not make the
# scanner allocate gigabytes before the crc check can call it torn.
MAX_RECORD_BYTES = 64 << 20
JOURNAL_FILE = "requests.jnl"

# Record kinds.
ACCEPTED = "accepted"
COMPLETED = "completed"
# Session records (ISSUE 13, serving/sessions.py): a stateful session
# is replayed WHOLE after a crash — open record (the base problem),
# every acknowledged event batch, the newest engine-state checkpoint
# marker, and a close record that retires the lot.
SESSION_OPEN = "session_open"
SESSION_EVENT = "session_event"
SESSION_CKPT = "session_ckpt"
SESSION_CLOSE = "session_close"
SESSION_KINDS = (SESSION_OPEN, SESSION_EVENT, SESSION_CKPT,
                 SESSION_CLOSE)


def encode_record(record: Dict[str, Any]) -> bytes:
    payload = json.dumps(
        record, separators=(",", ":"), default=str).encode()
    return _HEADER.pack(
        len(payload), binascii.crc32(payload) & 0xFFFFFFFF) + payload


def scan_journal(path: str) -> Tuple[List[Dict[str, Any]], int, bool]:
    """Read every valid record off a journal file.

    Returns ``(records, valid_bytes, torn)``: ``valid_bytes`` is the
    offset just past the last record that verified (length plausible,
    payload complete, crc matching, JSON decoding) — the truncation
    point for a torn tail; ``torn`` says whether anything past it was
    found.  A missing file is an empty journal, never an error."""
    records: List[Dict[str, Any]] = []
    try:
        data = open(path, "rb").read()
    except FileNotFoundError:
        return records, 0, False
    offset = 0
    while offset + _HEADER.size <= len(data):
        length, crc = _HEADER.unpack_from(data, offset)
        start = offset + _HEADER.size
        end = start + length
        if length > MAX_RECORD_BYTES or end > len(data):
            break
        payload = data[start:end]
        if binascii.crc32(payload) & 0xFFFFFFFF != crc:
            break
        try:
            records.append(json.loads(payload))
        except ValueError:
            break
        offset = end
    return records, offset, offset < len(data)


def pending_requests(records: List[Dict[str, Any]]
                     ) -> List[Dict[str, Any]]:
    """Accepted records with no terminal record — the replay set, in
    acceptance order.  A completion for an id the journal never
    accepted is ignored (it can only be debris from a pre-compaction
    file)."""
    accepted: Dict[str, Dict[str, Any]] = {}
    for rec in records:
        kind = rec.get("kind")
        rid = rec.get("id")
        if kind == ACCEPTED and rid is not None:
            accepted[rid] = rec
        elif kind == COMPLETED and rid in accepted:
            del accepted[rid]
    return list(accepted.values())


# How many completed-with-result records a compaction retains.  The
# tail is the crash-durable result cache: big enough to cover every
# ack a client could still be polling across a restart, small enough
# that compaction actually compacts (a record with a result payload
# is a few hundred bytes — the accepted record's problem yaml, the
# bulky part, is already dropped with the pair).
COMPLETED_KEEP = 256


def completed_results(records: List[Dict[str, Any]],
                      keep: int = COMPLETED_KEEP
                      ) -> List[Dict[str, Any]]:
    """The newest ``keep`` completed records that carry a ``result``
    payload, newest-completion-last — what a restarted worker loads
    into its recovered-result cache so a pre-crash 202 still resolves
    to its outcome.  Plain completed records (no payload: pre-ISSUE-16
    journals, or appends that could not serialize the result) are
    tombstones only and are never retained."""
    seen: Dict[str, Dict[str, Any]] = {}
    for rec in records:
        if rec.get("kind") == COMPLETED and rec.get("id") is not None \
                and rec.get("result") is not None:
            # Re-insert so a re-completion (replay finishing a request
            # a prior segment also finished) keeps the newest outcome.
            seen.pop(rec["id"], None)
            seen[rec["id"]] = rec
    out = list(seen.values())
    return out[-keep:] if keep >= 0 else out


def pending_sessions(records: List[Dict[str, Any]]
                     ) -> List[Dict[str, Any]]:
    """Open-but-not-closed sessions, each as ``{"open": rec,
    "ckpt": rec_or_None, "events": [recs]}`` in open order — the
    whole-session replay set.

    ``ckpt`` is the NEWEST checkpoint marker; ``events`` holds every
    acknowledged event batch in seq order, INCLUDING those at or
    before the checkpoint seq — recovery needs the pre-checkpoint
    events to rebuild the engine's factor layout structurally before
    the checkpointed message state can be restored onto it
    (serving/sessions.py SessionManager.recover).

    Exception — the recovery-time bound (ISSUE 16): a REBASED
    checkpoint marker carries the session's CURRENT problem
    serialized (``"dcop"`` key, serving/migration.engine_dcop_yaml),
    so the factor layout can be rebuilt from the marker alone and
    every batch at or before its seq is dead weight: those events are
    DROPPED here, which both bounds replay work and shrinks what
    compaction keeps for a long-lived session from its full event
    history to the post-checkpoint tail."""
    open_recs: Dict[str, Dict[str, Any]] = {}
    events: Dict[str, List[Dict[str, Any]]] = {}
    ckpts: Dict[str, Dict[str, Any]] = {}
    for rec in records:
        kind = rec.get("kind")
        sid = rec.get("id")
        if sid is None:
            continue
        if kind == SESSION_OPEN:
            open_recs[sid] = rec
            events[sid] = []
            ckpts.pop(sid, None)
        elif kind == SESSION_EVENT and sid in open_recs:
            events[sid].append(rec)
        elif kind == SESSION_CKPT and sid in open_recs:
            prior = ckpts.get(sid)
            if prior is None or (rec.get("seq", 0)
                                 >= prior.get("seq", 0)):
                ckpts[sid] = rec
        elif kind == SESSION_CLOSE and sid in open_recs:
            del open_recs[sid]
            events.pop(sid, None)
            ckpts.pop(sid, None)
    out = []
    for sid, rec in open_recs.items():
        ckpt = ckpts.get(sid)
        evs = sorted(events.get(sid, []),
                     key=lambda r: r.get("seq", 0))
        if ckpt is not None and ckpt.get("dcop"):
            ckpt_seq = ckpt.get("seq", 0)
            evs = [r for r in evs if r.get("seq", 0) > ckpt_seq]
        out.append({"open": rec, "ckpt": ckpt, "events": evs})
    return out


def compact_journal(journal_dir: str
                    ) -> Tuple[List[Dict[str, Any]],
                               List[Dict[str, Any]],
                               List[Dict[str, Any]]]:
    """Compact a journal IN PLACE without opening it for appends:
    scan, truncate a torn tail, and atomically rewrite the file down
    to the pending requests, every open session's replay records
    (post-rebased-checkpoint only — see :func:`pending_sessions`),
    and the newest :data:`COMPLETED_KEEP` completed-with-result
    records (:func:`completed_results` — the crash-durable outcomes a
    restarted worker serves to clients still polling a pre-crash ack).

    Returns ``(pending_requests, pending_sessions, results)``.  This
    is the owner-less half of :meth:`RequestJournal.recover_full`:
    the fleet router runs it over a DEAD replica's segment before
    handing the segment to a replacement (or migrating its sessions
    to survivors), so the restarted worker's ``--recover`` replay
    visits only still-pending records instead of the segment's full
    history."""
    path = os.path.join(journal_dir, JOURNAL_FILE)
    records, valid_bytes, torn = scan_journal(path)
    if torn:
        logger.warning(
            "journal %s has a torn tail: truncating to the last "
            "valid record at byte %d", path, valid_bytes)
    pending = pending_requests(records)
    sessions = pending_sessions(records)
    results = completed_results(records)
    if os.path.exists(path):
        # Pending requests, retained results, plus every open
        # session's open/ckpt/event records, written to a temp file
        # and renamed over the old journal — a crash mid-compact
        # leaves the (longer but equivalent) original.
        fd, tmp = tempfile.mkstemp(
            dir=journal_dir, prefix=".jnl_tmp_")
        try:
            with os.fdopen(fd, "wb") as f:
                for rec in pending:
                    f.write(encode_record(rec))
                for rec in results:
                    f.write(encode_record(rec))
                for sess in sessions:
                    f.write(encode_record(sess["open"]))
                    if sess["ckpt"] is not None:
                        f.write(encode_record(sess["ckpt"]))
                    for rec in sess["events"]:
                        f.write(encode_record(rec))
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    return pending, sessions, results


def append_record(journal_dir: str, record: Dict[str, Any]) -> None:
    """One-shot durable append to a journal nobody holds open — the
    fleet router's tool for closing out a DEAD replica's sessions
    after migrating them to survivors (the restarted worker must not
    resurrect what a survivor already owns)."""
    os.makedirs(journal_dir, exist_ok=True)
    path = os.path.join(journal_dir, JOURNAL_FILE)
    with open(path, "ab") as f:
        f.write(encode_record(record))
        f.flush()
        os.fsync(f.fileno())


class RequestJournal:
    """Append-side handle on one journal directory.

    Thread-safe (submitting threads and the scheduler thread both
    append).  ``append`` returns only after the record reached the OS
    (``flush``; plus ``fsync`` with ``sync=True``) — the caller may
    then acknowledge the request."""

    def __init__(self, journal_dir: str, sync: bool = False):
        os.makedirs(journal_dir, exist_ok=True)
        self.journal_dir = journal_dir
        self.path = os.path.join(journal_dir, JOURNAL_FILE)
        self.sync = sync
        self._lock = threading.Lock()
        self._f = open(self.path, "ab")
        self.appended = 0

    def append(self, record: Dict[str, Any]) -> None:
        blob = encode_record(record)
        with self._lock:
            if self._f.closed:
                raise RuntimeError("journal is closed")
            self._f.write(blob)
            self._f.flush()
            if self.sync:
                os.fsync(self._f.fileno())
            self.appended += 1

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()

    @classmethod
    def recover(cls, journal_dir: str, sync: bool = False
                ) -> Tuple["RequestJournal", List[Dict[str, Any]]]:
        """:meth:`recover_full` without the session and result sets —
        kept for callers that predate stateful sessions (the
        compaction still preserves open-session and retained-result
        records either way: a request-only consumer must never
        silently destroy session or result durability)."""
        journal, pending, _sessions, _results = cls.recover_full(
            journal_dir, sync=sync)
        return journal, pending

    @classmethod
    def recover_full(cls, journal_dir: str, sync: bool = False
                     ) -> Tuple["RequestJournal",
                                List[Dict[str, Any]],
                                List[Dict[str, Any]],
                                List[Dict[str, Any]]]:
        """Open a journal directory for crash recovery.

        Scans the journal, truncates a torn tail past the last valid
        record, computes the pending (accepted-without-terminal)
        request set, the open-session set
        (:func:`pending_sessions`), and the retained
        completed-with-result set (:func:`completed_results`), and
        atomically compacts the file down to exactly those records
        before reopening it for appends (:func:`compact_journal`).
        Returns ``(journal, pending_requests, pending_sessions,
        results)`` in acceptance/open/completion order."""
        pending, sessions, results = compact_journal(journal_dir)
        journal = cls(journal_dir, sync=sync)
        if pending or sessions:
            logger.info(
                "journal recovery: %d pending request(s) and %d "
                "open session(s) to replay (%d completed result(s) "
                "retained)",
                len(pending), len(sessions), len(results))
        return journal, pending, sessions, results


def accepted_record(rid: str, dcop_yaml: str,
                    params: Dict[str, Any],
                    deadline_s: Optional[float] = None,
                    t_submit: Optional[float] = None,
                    trace_id: Optional[str] = None
                    ) -> Dict[str, Any]:
    rec = {"kind": ACCEPTED, "id": rid, "dcop": dcop_yaml,
           "params": params}
    if deadline_s is not None:
        rec["deadline_s"] = deadline_s
    if t_submit is not None:
        rec["t"] = t_submit
    if trace_id:
        # The request's causality key survives the crash with the
        # record: a replayed request keeps its original trace_id, so
        # `pydcop trace query` stitches pre- and post-crash spans
        # into one request tree.
        rec["trace_id"] = trace_id
    return rec


def completed_record(rid: str, status: str,
                     result: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    """Terminal record.  ``result`` (the request's wire-form result
    dict) makes the OUTCOME crash-durable, not just the fact of
    completion: a client holding a durable 202 whose request finished
    moments before the process died polls the restarted worker and
    gets its 200 from the journal instead of a 404 (the in-memory
    result cache died with the process)."""
    rec = {"kind": COMPLETED, "id": rid, "status": status}
    if result is not None:
        rec["result"] = result
    return rec


# --------------------------------------------------------------------- #
# Session records (serving/sessions.py)


def session_open_record(sid: str, dcop_yaml: str,
                        params: Dict[str, Any],
                        trace_id: Optional[str] = None,
                        epoch: int = 1) -> Dict[str, Any]:
    """``epoch`` is the session's ownership fencing epoch (ISSUE 19):
    recovery restores it so a journal-recovered copy rejects writes
    minted for a NEWER owner, and a migrated-in copy (whose bundle
    carries the bumped epoch) outranks the fenced original."""
    rec = {"kind": SESSION_OPEN, "id": sid, "dcop": dcop_yaml,
           "params": params, "epoch": max(int(epoch), 1)}
    if trace_id:
        rec["trace_id"] = trace_id
    return rec


def session_event_record(sid: str, seq: int,
                         events: List[Dict[str, Any]],
                         trace_id: Optional[str] = None
                         ) -> Dict[str, Any]:
    """One acknowledged PATCH batch: ``seq`` is the batch's position
    in the session's event order (monotone per session — replay
    applies batches in seq order), ``events`` the wire-form event
    list exactly as acknowledged."""
    rec = {"kind": SESSION_EVENT, "id": sid, "seq": int(seq),
           "events": events}
    if trace_id:
        rec["trace_id"] = trace_id
    return rec


def session_ckpt_record(sid: str, seq: int, path: str,
                        cycle: int = 0,
                        dcop: Optional[str] = None
                        ) -> Dict[str, Any]:
    """Engine-state checkpoint marker: the NPZ at ``path`` holds the
    warm message state AFTER event batch ``seq`` was applied —
    recovery restores it and replays only the batches past ``seq``.

    ``dcop`` REBASES the checkpoint: the session's current problem
    (open-record problem + every batch through ``seq``, serialized
    back to dcop yaml by serving/migration.engine_dcop_yaml).  A
    rebased marker lets recovery rebuild the factor layout from the
    marker alone, so compaction drops the pre-checkpoint event tail
    entirely (:func:`pending_sessions`) — replay time is bounded by
    the checkpoint cadence, not session age."""
    rec = {"kind": SESSION_CKPT, "id": sid, "seq": int(seq),
           "path": path, "cycle": int(cycle)}
    if dcop:
        rec["dcop"] = dcop
    return rec


def session_close_record(sid: str, status: str) -> Dict[str, Any]:
    return {"kind": SESSION_CLOSE, "id": sid, "status": status}

"""Ising A-MaxSum benchmark — BASELINE config #2: 32x32 (1,024-var)
random Ising grid with binary + unary factors, solved with
amaxsum + damping 0.7 on the device engine, against this repo's own
threaded agent runtime running the true asynchronous amaxsum
computations on the same instance.

Device amaxsum is the lockstep engine (an async firing schedule has no
device meaning — algorithms/amaxsum.py docstring), so beyond speed this
bench records both final costs: the documented claim that lockstep and
async schedules land in the same cost band on Ising grids.

The device leg builds ONE engine and times the second run, so the
cycles/s value is steady-state execution (warm jit cache), and
speedup_wall compares compile-free device wall clock against the
thread runtime's wall clock.

Run: python benchmarks/bench_ising_amaxsum.py [rows]
Prints one JSON line.
"""

import json
import sys
import time

ROWS = 32
DEVICE_CYCLES = 300
THREAD_TIMEOUT_S = 20.0
THREAD_AGENTS = 8


def main():
    from pydcop_tpu.utils.cleanenv import ensure_live_backend

    ensure_live_backend(tag="bench_ising_amaxsum")
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else ROWS
    from pydcop_tpu.algorithms import AlgorithmDef, load_algorithm_module
    from pydcop_tpu.algorithms.maxsum import build_engine
    from pydcop_tpu.computations_graph import load_graph_module
    from pydcop_tpu.distribution.objects import Distribution
    from pydcop_tpu.generators.ising import generate_ising
    from pydcop_tpu.infrastructure.run import run_local_thread_dcop

    dcop, _, _ = generate_ising(rows, no_agents=True, seed=11)
    module = load_algorithm_module("amaxsum")

    # Device leg: ONE engine so the timed run hits the warm jit cache
    # (solve_on_device builds a fresh engine per call — every call
    # would be a cold start).
    algo_def = AlgorithmDef.build_with_default_param(
        "amaxsum", mode="min", params={"damping": 0.7})
    engine = build_engine(dcop, algo_def.params)
    engine.run(max_cycles=DEVICE_CYCLES, stop_on_convergence=False)
    t0 = time.perf_counter()
    res = engine.run(max_cycles=DEVICE_CYCLES, stop_on_convergence=False)
    device_wall = time.perf_counter() - t0
    device_cost, _ = dcop.solution_cost(res.assignment)
    device_cps = res.cycles / res.time_s if res.time_s > 0 else 0.0

    # Thread leg: true async amaxsum computations on agent threads.
    from pydcop_tpu.dcop.objects import AgentDef

    dcop.add_agents(
        [AgentDef(f"a{i}") for i in range(THREAD_AGENTS)])
    cg = load_graph_module(
        module.GRAPH_TYPE).build_computation_graph(dcop)
    agents = sorted(dcop.agents)
    mapping = {a: [] for a in agents}
    for i, node in enumerate(cg.nodes):
        mapping[agents[i % len(agents)]].append(node.name)
    orch = run_local_thread_dcop(
        algo_def, cg, Distribution(mapping), dcop)
    try:
        if not orch.wait_ready(30):
            raise RuntimeError("agents not ready")
        orch.deploy_computations()
        t0 = time.perf_counter()
        orch.run(timeout=THREAD_TIMEOUT_S)
        thread_wall = time.perf_counter() - t0
        orch.stop_agents(10)
        metrics = orch.end_metrics()
        # end_metrics already filters the assignment and guards the
        # not-all-reported case; None -> NaN keeps the JSON line alive.
        thread_cost = (
            float(metrics["cost"]) if metrics["cost"] is not None
            else float("nan")
        )
    finally:
        orch.stop_agents(5)
        orch.stop()

    print(json.dumps({
        "metric": "ising_amaxsum_cycles_per_sec",
        "value": round(device_cps, 2),
        "unit": "cycles/s",
        "n_vars": rows * rows,
        "damping": 0.7,
        "device_cost": round(device_cost, 3),
        "device_wall_s": round(device_wall, 3),
        "thread_cost_async": round(thread_cost, 3),
        "thread_wall_s": round(thread_wall, 2),
        "speedup_wall": (
            round(thread_wall / device_wall, 1)
            if device_wall > 0 else None
        ),
    }))


if __name__ == "__main__":
    main()

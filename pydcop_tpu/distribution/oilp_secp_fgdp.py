"""oilp_secp_fgdp: optimal ILP, SECP flavor, factor graph.

Reference parity: pydcop/distribution/oilp_secp_fgdp.py:72-131.  Same
policy as oilp_secp_cgdp with the factor-graph pinning convention:
each actuator variable's ``c_<actuator>`` energy cost factor is pinned
alongside it before the communication-cost-only MILP solves the
remaining (model variable / model factor / rule factor) placements,
with capacity hard constraints and every unpinned agent hosting at
least one computation.
"""

from pydcop_tpu.distribution.objects import (
    ImpossibleDistributionException,
)
from pydcop_tpu.distribution.oilp_secp_cgdp import (
    _secp_ilp,
    distribution_cost,  # noqa: F401  (same comm-only cost model)
)
from pydcop_tpu.distribution.secp_rules import split_fg_nodes


def distribute(computation_graph, agentsdef, hints=None,
               computation_memory=None, communication_load=None,
               timeout=600, **_):
    if computation_memory is None or communication_load is None:
        raise ImpossibleDistributionException(
            "oilp_secp_fgdp requires computation_memory and "
            "communication_load functions")
    variables, factors = split_fg_nodes(computation_graph)
    return _secp_ilp(
        computation_graph, agentsdef, computation_memory,
        communication_load, timeout,
        cost_factors=(variables, factors),
    )

"""CLI tests for the dynamic-DCOP commands: run + replica_dist.

Mirrors the reference's CLI test strategy (subprocess + JSON results,
tests/dcop_cli/).
"""

import json
import os
import subprocess
import sys

REF_INSTANCES = "/root/reference/tests/instances"
INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")
ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
}


def run_cli(args, timeout=120):
    out = subprocess.check_output(
        [sys.executable, "-m", "pydcop_tpu.dcop_cli"] + args,
        timeout=timeout, env=ENV,
    )
    return json.loads(out)


def test_replica_dist_places_replicas():
    out = subprocess.check_output(
        [sys.executable, "-m", "pydcop_tpu.dcop_cli",
         "replica_dist", "-a", "dsa", "-d", "adhoc", "-k", "2",
         os.path.join(REF_INSTANCES,
                      "graph_coloring_4agts_10vars.yaml")],
        timeout=120, env=ENV,
    ).decode()
    assert "replica_dist:" in out
    # Every variable computation must have 2 replicas.
    import yaml

    data = yaml.safe_load(out)
    mapping = data["replica_dist"]
    assert len(mapping) == 10
    for comp, hosts in mapping.items():
        assert len(hosts) == 2, f"{comp}: {hosts}"


def test_run_with_scenario_repairs():
    result = run_cli([
        "-t", "12",
        "run", "-a", "dsa", "-d", "adhoc", "-k", "2",
        "-s", os.path.join(INSTANCES, "scenario_remove_a1.yaml"),
        os.path.join(REF_INSTANCES, "graph_coloring_4agts_10vars.yaml"),
    ], timeout=180)
    assert result["status"] in ("FINISHED", "TIMEOUT")
    # All 10 variables still have a value despite a1's departure.
    assert len(result["assignment"]) == 10
    replication = result["replication"]
    assert replication["ktarget"] == 2
    # a1 hosted at least v1 (must_host hint): repair happened.
    assert replication["repaired"], "no computation was repaired"

"""CLI subcommand modules.

Reference parity: pydcop/commands/ — each module exposes
``set_parser(subparsers)`` registering its arguments and a ``run_cmd``
callable stored as the parser default ``func``.
"""

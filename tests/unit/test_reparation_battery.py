"""Battery over the repair-as-DCOP builders (reparation/) and the
removal analysis, at the reference's test_reparation*.py depth —
asserting the constraint SEMANTICS (hard/soft shapes), not just
wiring."""

from pydcop_tpu.distribution.objects import Distribution
from pydcop_tpu.replication.objects import ReplicaDistribution
from pydcop_tpu.reparation import (
    DEFAULT_INFINITY,
    binary_variable_name,
    create_agent_capacity_constraint,
    create_agent_comp_comm_constraint,
    create_agent_hosting_constraint,
    create_binary_variables_for,
    create_computation_hosted_constraint,
)
from pydcop_tpu.reparation.removal import (
    candidate_agents,
    candidate_computations_for_agent,
    orphaned_computations,
    removal_info,
    unrepairable_computations,
)


def variables_for(comp, agents, suffix=""):
    return create_binary_variables_for(
        [comp], {comp: agents}, suffix)


class TestBinaryVariables:
    def test_naming(self):
        assert binary_variable_name("v1", "a2") == "x_v1_a2"
        assert binary_variable_name("v1", "a2", "__r3") == "x_v1_a2__r3"

    def test_one_variable_per_pair(self):
        vs = create_binary_variables_for(
            ["c1", "c2"], {"c1": ["a1", "a2"], "c2": ["a2"]})
        assert set(vs) == {("c1", "a1"), ("c1", "a2"), ("c2", "a2")}
        assert vs[("c2", "a2")].name == "x_c2_a2"

    def test_suffix_makes_rounds_distinct(self):
        v1 = variables_for("c", ["a"], "__r1")[("c", "a")]
        v2 = variables_for("c", ["a"], "__r2")[("c", "a")]
        assert v1.name != v2.name


class TestHostedConstraint:
    def test_exactly_one_is_free(self):
        vs = list(variables_for("c1", ["a1", "a2", "a3"]).values())
        c = create_computation_hosted_constraint("c1", vs)
        assert c(1, 0, 0) == 0
        assert c(0, 1, 0) == 0

    def test_zero_or_many_hard_violation(self):
        vs = list(variables_for("c1", ["a1", "a2"]).values())
        c = create_computation_hosted_constraint("c1", vs)
        assert c(0, 0) == DEFAULT_INFINITY
        assert c(1, 1) == DEFAULT_INFINITY


class TestCapacityConstraint:
    def _constraint(self, remaining):
        vs = {
            "c1": variables_for("c1", ["a"])[("c1", "a")],
            "c2": variables_for("c2", ["a"])[("c2", "a")],
        }
        return create_agent_capacity_constraint(
            "a", remaining, {"c1": 3.0, "c2": 4.0}, vs)

    def test_fit_is_free(self):
        c = self._constraint(remaining=7)
        assert c(1, 1) == 0
        assert c(0, 0) == 0

    def test_overload_hard_violation(self):
        c = self._constraint(remaining=5)
        # sorted order: c1 (3.0) then c2 (4.0)
        assert c(1, 1) == DEFAULT_INFINITY
        assert c(1, 0) == 0
        assert c(0, 1) == 0


class TestSoftConstraints:
    def test_hosting_cost_sums_accepted(self):
        vs = {
            "c1": variables_for("c1", ["a"])[("c1", "a")],
            "c2": variables_for("c2", ["a"])[("c2", "a")],
        }
        c = create_agent_hosting_constraint(
            "a", {"c1": 2.0, "c2": 5.0}, vs)
        assert c(1, 1) == 7.0
        assert c(1, 0) == 2.0
        assert c(0, 0) == 0.0

    def test_comm_cost_scales_with_hosting_decision(self):
        v = variables_for("c1", ["a1"])[("c1", "a1")]
        routes = {("a1", "a2"): 3.0, ("a1", "a3"): 1.0}
        c = create_agent_comp_comm_constraint(
            "a1", "c1",
            neighbor_agents={"n1": "a2", "n2": "a3"},
            route=lambda a, b: routes[(a, b)],
            comm_load=lambda comp, n: 2.0,
            variable=v,
        )
        # (3*2) + (1*2) = 8 when hosted, 0 when not
        assert c(1) == 8.0
        assert c(0) == 0.0


class TestRemovalAnalysis:
    DIST = Distribution({
        "a1": ["c1", "c2"], "a2": ["c3"], "a3": [],
    })
    REPLICAS = ReplicaDistribution({
        "c1": ["a2", "a3"], "c2": ["a1"], "c3": ["a1"],
    })

    def test_orphaned_computations(self):
        assert orphaned_computations(["a1"], self.DIST) == ["c1", "c2"]
        assert orphaned_computations(["a1", "a2"], self.DIST) == [
            "c1", "c2", "c3"]
        assert orphaned_computations(["a3"], self.DIST) == []

    def test_candidates_exclude_departed(self):
        cands = candidate_agents(
            ["c1", "c2"], self.REPLICAS, departed=["a1"])
        assert cands["c1"] == ["a2", "a3"]
        # c2's only replica was on the departed agent itself
        assert cands["c2"] == []

    def test_candidate_computations_for_agent(self):
        cands = {"c1": ["a2", "a3"], "c2": ["a3"]}
        assert candidate_computations_for_agent("a3", cands) == [
            "c1", "c2"]
        assert candidate_computations_for_agent("a2", cands) == ["c1"]

    def test_unrepairable(self):
        cands = {"c1": ["a2"], "c2": []}
        assert unrepairable_computations(cands) == ["c2"]

    def test_removal_info_summary(self):
        orphaned, cands, lost = removal_info(
            ["a1"], self.DIST, self.REPLICAS)
        assert orphaned == ["c1", "c2"]
        assert cands["c1"] == ["a2", "a3"]
        assert lost == ["c2"]

    def test_unknown_replica_entry_is_lost(self):
        dist = Distribution({"a1": ["ghost"]})
        replicas = ReplicaDistribution({})
        orphaned, cands, lost = removal_info(["a1"], dist, replicas)
        assert orphaned == ["ghost"]
        assert lost == ["ghost"]

"""Golden parity beyond the brute-force cap (VERDICT weak #8: only
small fixtures were proven optimal).

Ground truth for larger problems comes from structure, not
enumeration: DPOP is exact on any problem, and on TREES MaxSum (belief
propagation) and SyncBB are exact too.  Random trees of 60+ variables
(search space ~4^60, far beyond enumeration) therefore give exact
optimality assertions for three independent implementations against
each other — plus device-vs-thread parity for dpop's tensorized path.
"""

import numpy as np
import pytest

from pydcop_tpu.api import solve
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation


def random_tree_dcop(n_vars: int, d: int, seed: int) -> DCOP:
    """Random tree: each node i>0 links to a random earlier node with a
    random cost table — DPOP-exact and BP-exact by structure."""
    rng = np.random.default_rng(seed)
    dom = Domain("d", "", list(range(d)))
    dcop = DCOP(f"tree{n_vars}_{seed}", objective="min")
    variables = [Variable(f"v{i}", dom) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    for i in range(1, n_vars):
        j = int(rng.integers(0, i))
        table = rng.integers(0, 20, size=(d, d)).astype(np.float64)
        dcop.add_constraint(NAryMatrixRelation(
            [variables[j], variables[i]], table, f"c{i}"))
    dcop.add_agents(
        [AgentDef(f"a{k}", capacity=10_000) for k in range(4)])
    return dcop


SEEDS = [0, 1, 2]


@pytest.fixture(scope="module")
def tree_optima():
    """DPOP (exact) optimum per seed — ground truth for the others."""
    out = {}
    for seed in SEEDS:
        dcop = random_tree_dcop(60, 4, seed)
        res = solve(dcop, "dpop", backend="device")
        out[seed] = (dcop, res["cost"])
    return out


def test_dpop_deterministic_across_runs(tree_optima):
    for seed, (dcop, cost) in tree_optima.items():
        res = solve(
            random_tree_dcop(60, 4, seed), "dpop", backend="device")
        assert res["cost"] == cost


@pytest.mark.parametrize("seed", SEEDS)
def test_maxsum_exact_on_trees(tree_optima, seed):
    """Belief propagation is exact on acyclic graphs: device MaxSum
    must hit DPOP's optimum on every tree.  The default stability
    (0.1) freezes edges via send-suppression before the messages reach
    the exact fixpoint (reference semantics), so exactness requires a
    tight stability threshold."""
    dcop, optimum = tree_optima[seed]
    res = solve(
        random_tree_dcop(60, 4, seed), "maxsum", backend="device",
        max_cycles=300,
        algo_params={"noise": 0.001, "stability": 1e-6},
    )
    assert res["cost"] == pytest.approx(optimum, abs=1e-4)


@pytest.mark.parametrize("seed", SEEDS[:2])
def test_syncbb_matches_dpop_on_smaller_tree(seed):
    """SyncBB (complete search) equals DPOP on a 14-var tree — still
    ~4^14 = 2.7e8 states, three orders past the brute-force cap."""
    dcop1 = random_tree_dcop(14, 4, seed)
    r_dpop = solve(dcop1, "dpop", backend="device")
    dcop2 = random_tree_dcop(14, 4, seed)
    r_bb = solve(dcop2, "syncbb", backend="device")
    assert r_bb["cost"] == pytest.approx(r_dpop["cost"])


def test_dpop_thread_matches_device(tree_optima):
    """The tensorized UTIL/VALUE sweeps and the agent-mode DPOP
    computations must produce the same exact optimum."""
    seed = SEEDS[0]
    _, optimum = tree_optima[seed]
    dcop = random_tree_dcop(60, 4, seed)
    res = solve(
        dcop, "dpop", backend="thread", timeout=30,
        distribution="adhoc",
    )
    assert res["cost"] == pytest.approx(optimum)


def test_local_search_bounded_by_optimum(tree_optima):
    """Sanity: approximate local search never beats the exact optimum
    (would indicate cost-accounting divergence), and lands within a
    finite band of it."""
    seed = SEEDS[0]
    dcop, optimum = tree_optima[seed]
    res = solve(
        random_tree_dcop(60, 4, seed), "dsa", backend="device",
        max_cycles=150,
    )
    assert res["cost"] >= optimum - 1e-9
    n_constraints = 59
    assert res["cost"] <= optimum + 10 * n_constraints
"""``pydcop graph``: computation-graph metrics for a DCOP.

Reference parity: pydcop/commands/graph.py — density, node/edge counts,
degree histogram for a given graph model.
"""

from pydcop_tpu.commands._utils import emit_result


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "graph", help="computation graph metrics for a dcop")
    parser.add_argument("dcop_files", nargs="+")
    parser.add_argument(
        "-g", "--graph", default=None,
        help="graph model (factor_graph, constraints_hypergraph, "
             "pseudotree, ordered_graph); defaults from --algo",
    )
    parser.add_argument("-a", "--algo", default=None,
                        help="algorithm whose GRAPH_TYPE to use")
    parser.add_argument("--display", action="store_true",
                        help="(kept for compatibility; no-op headless)")
    parser.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    from pydcop_tpu.algorithms import load_algorithm_module
    from pydcop_tpu.computations_graph import load_graph_module
    from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

    if not args.graph and not args.algo:
        print("Error: one of --graph or --algo is required")
        return 2
    graph_type = args.graph
    if not graph_type:
        graph_type = load_algorithm_module(args.algo).GRAPH_TYPE
    dcop = load_dcop_from_file(args.dcop_files)
    graph = load_graph_module(graph_type).build_computation_graph(dcop)

    from pydcop_tpu.utils.graphs import (
        constraint_adjacency,
        cycles_count,
        graph_diameter,
    )

    degrees = {}
    for node in graph.nodes:
        degrees[node.name] = len(node.neighbors)
    variables = list(dcop.variables.values())
    constraints = list(dcop.constraints.values())
    adj = constraint_adjacency(variables, constraints)
    result = {
        "graph": graph_type,
        "dcop": dcop.name,
        "variables": len(dcop.variables),
        "constraints": len(dcop.constraints),
        "nodes": len(graph.nodes),
        "edges": len(graph.links),
        "density": graph.density(),
        "max_degree": max(degrees.values(), default=0),
        "min_degree": min(degrees.values(), default=0),
        "avg_degree": (
            sum(degrees.values()) / len(degrees) if degrees else 0
        ),
        "cycles": cycles_count(variables, constraints, adj=adj),
        "component_diameters": graph_diameter(
            variables, constraints, adj=adj),
    }
    emit_result(result, args.output)
    return 0

"""oilp_secp_cgdp: optimal ILP, SECP flavor, constraint graph.

Reference parity: pydcop/distribution/oilp_secp_cgdp.py.  SECP policy
on top of the generic MILP engine:

1. actuator variables (hosting cost 0) are pinned on their agent
   *before* solving;
2. the ILP minimizes pure communication cost (route x load) over the
   remaining placements — hosting costs are NOT in the objective, they
   only express the pinning;
3. every agent that got no pinned computation must host at least one
   computation (reference's "each agent must host at least one"
   constraint).

Capacity is a hard constraint throughout.
"""

from itertools import combinations

from pydcop_tpu.distribution._base import ilp_place
from pydcop_tpu.distribution.objects import (
    ImpossibleDistributionException,
)
from pydcop_tpu.distribution.secp_rules import pin_actuators


def _secp_ilp(computation_graph, agentsdef, computation_memory,
              communication_load, timeout, cost_factors=None):
    agentsdef = list(agentsdef)
    kwargs = {}
    if cost_factors is not None:
        kwargs["candidates"] = cost_factors[0]
        kwargs["cost_factors"] = cost_factors[1]
    mapping, _capa, _remaining, _facs = pin_actuators(
        computation_graph, agentsdef, computation_memory, **kwargs)
    pinned = {
        comp: agent for agent, comps in mapping.items()
        for comp in comps
    }
    try:
        return ilp_place(
            computation_graph, agentsdef, None,
            computation_memory, communication_load,
            comm_weight=1.0, hosting_weight=0.0,
            timeout=timeout, pinned=pinned,
            require_nonempty_agents=True,
        )
    except ImpossibleDistributionException:
        # Degenerate non-SECP inputs (more agents than computations)
        # make the every-agent-hosts-one constraint infeasible; the
        # placement itself is still well-defined without it.
        return ilp_place(
            computation_graph, agentsdef, None,
            computation_memory, communication_load,
            comm_weight=1.0, hosting_weight=0.0,
            timeout=timeout, pinned=pinned,
            require_nonempty_agents=False,
        )


def distribute(computation_graph, agentsdef, hints=None,
               computation_memory=None, communication_load=None,
               timeout=600, **_):
    if computation_memory is None or communication_load is None:
        raise ImpossibleDistributionException(
            "oilp_secp_cgdp requires computation_memory and "
            "communication_load functions")
    return _secp_ilp(
        computation_graph, agentsdef, computation_memory,
        communication_load, timeout)


def distribution_cost(distribution, computation_graph, agentsdef,
                      computation_memory=None, communication_load=None):
    """Communication cost only (no hosting/route costs), as the
    reference's SECP cost model (oilp_secp_fgdp.py:134-172): sum of
    communication_load over links whose ends live on different
    agents.  Returns (total, comm, hosting=0)."""
    comm = 0.0
    for link in computation_graph.links:
        for c1, c2 in combinations(link.nodes, 2):
            if distribution.agent_for(c1) != distribution.agent_for(c2):
                if communication_load is not None:
                    comm += float(communication_load(
                        computation_graph.computation(c1), c2))
                else:
                    comm += 1.0
    return comm, comm, 0.0

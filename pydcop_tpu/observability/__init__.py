"""Unified observability: tracing, metrics registry, engine telemetry.

Eight parts (docs/observability.md):

- :mod:`.efficiency` — the device-efficiency accounting plane:
  per-dispatch utilization attainment, request time ledgers and the
  where-the-time-went rollup behind ``/profile``, the ``/stats``
  efficiency block and ``pydcop profile report``;
- :mod:`.trace` — process-wide :data:`~pydcop_tpu.observability.trace.
  tracer` producing timestamped, parent-correlated spans with Chrome
  ``trace_event`` and JSONL exporters, plus multi-process trace
  merge/diff tooling;
- :mod:`.metrics` — :data:`~pydcop_tpu.observability.metrics.registry`
  of counters/gauges/histograms with Prometheus text export and JSONL
  snapshots;
- :mod:`.engine_probe` — per-chunk honest device timings + cost
  convergence for the jitted solvers;
- :mod:`.profiler` — XLA cost attribution: measured flops/bytes/peak
  memory per compiled engine program;
- :mod:`.server` — live HTTP telemetry endpoint (``/metrics``,
  ``/healthz``, ``/events``, ``/debug/bundle``) for scraping a
  running solve;
- :mod:`.flight` — the always-on flight recorder: a bounded ring of
  trace events (recording even while file tracing is off) that dumps
  postmortem bundles on anomaly triggers (``PYDCOP_FLIGHT_RECORDER=0``
  opts out);
- the instrumentation wired through infrastructure, engine and
  resilience (all guarded on one flag check, zero overhead when off).

:class:`ObservabilitySession` is the run-scoped front door used by
``api.solve``: it enables the tracer/registry/profiler for one solve,
optionally serves live telemetry while it runs, and exports trace +
Prometheus files on the way out.
"""

from typing import Optional

from pydcop_tpu.observability.efficiency import (  # noqa: F401
    EfficiencyTracker,
    get_tracker,
)
from pydcop_tpu.observability.metrics import (  # noqa: F401
    MetricsRegistry,
    get_registry,
    registry,
)
from pydcop_tpu.observability.profiler import (  # noqa: F401
    XlaCostProfiler,
    get_profiler,
    profiler,
)
from pydcop_tpu.observability.trace import (  # noqa: F401
    Tracer,
    get_tracer,
    tracer,
)
from pydcop_tpu.observability import flight as _flight
from pydcop_tpu.observability.flight import (  # noqa: F401
    FlightRecorder,
    get_flight,
)

# The flight recorder is ALWAYS ON by default (PYDCOP_FLIGHT_RECORDER
# =0 opts out): the black box only helps if it was recording before
# the anomaly.  Ring-only until a trigger fires — nothing is written
# to disk on the happy path.
_flight.install()


class ObservabilitySession:
    """Enable tracing/metrics for one solve; export on finish.

    ``trace_path`` + ``trace_format`` ('chrome'|'jsonl') control the
    trace export; ``metrics_path`` activates the registry's optional
    instrumentation — and the XLA cost profiler, unless
    ``PYDCOP_XLA_PROFILE=0`` vetoes it — and, on finish, writes a
    Prometheus text dump next to the JSONL snapshots
    (``<metrics_path>.prom``).  ``serve_port`` (0 = OS-assigned, see
    :attr:`server`) additionally serves ``/metrics`` + ``/healthz`` +
    ``/events`` over HTTP for the duration of the session, so a long
    run is scrapeable WHILE it runs (observability/server.py).
    """

    def __init__(self, trace_path: Optional[str] = None,
                 trace_format: str = "chrome",
                 metrics_path: Optional[str] = None,
                 serve_port: Optional[int] = None):
        if trace_format not in ("chrome", "jsonl"):
            raise ValueError(
                f"trace_format must be 'chrome' or 'jsonl', got "
                f"{trace_format!r}"
            )
        self.trace_path = trace_path
        self.trace_format = trace_format
        self.metrics_path = metrics_path
        self.serve_port = serve_port
        self.server = None
        self._was_active = registry.active
        self._was_profiling = profiler.enabled

    def start(self) -> "ObservabilitySession":
        # Bind the server FIRST: it is the only step that can fail
        # (port in use), and failing after enabling would leak
        # tracer/registry/profiler enabled process-wide with no
        # finish() ever running (the caller never got a session).
        if self.serve_port is not None:
            import sys

            from pydcop_tpu.observability.server import TelemetryServer

            self.server = TelemetryServer(port=self.serve_port).start()
            # The OS picks the port when serve_port=0: announce it, or
            # nothing can scrape the run it was requested for.
            print(
                "telemetry: serving /metrics /healthz /events on "
                f"{self.server.url}", file=sys.stderr,
            )
        if self.trace_path:
            tracer.enable()
        if self.metrics_path or self.serve_port is not None:
            registry.active = True
            profiler.enabled = True
        return self

    def finish(self):
        if self.server is not None:
            self.server.stop()
            self.server = None
        if self.trace_path:
            tracer.disable()
            tracer.export(self.trace_path, self.trace_format)
        if self.metrics_path or self.serve_port is not None:
            registry.active = self._was_active
            profiler.enabled = self._was_profiling
        if self.metrics_path:
            with open(f"{self.metrics_path}.prom", "w",
                      encoding="utf-8") as f:
                f.write(registry.to_prometheus())

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.finish()
        return False

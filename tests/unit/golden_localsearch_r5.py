"""Frozen round-5 copy of the DSA and MGM device kernels (plus the
localsearch helpers they use).

Executable perf/semantics baseline for ``test_perf_regression.py``,
same pattern as ``golden_maxsum_kernel.py``: the live kernels
(pydcop_tpu/ops/dsa.py, ops/mgm.py, ops/localsearch.py) are raced
against this copy IN THE SAME PROCESS, so the ratio is immune to
machine-load drift, and must reproduce its exact seeded trajectory.

Do NOT update this file when optimizing the live kernels unless the
regression test's parity assertion demands it: it exists to stay
behind.
"""

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from pydcop_tpu.engine.compile import CompiledFactorGraph


# ---- frozen localsearch helpers -------------------------------------- #


def _fix_other_axes(costs, var_ids, values, keep):
    arity = var_ids.shape[1]
    out = costs
    for q in range(arity - 1, -1, -1):
        if q == keep:
            continue
        vq = values[var_ids[:, q]]
        idx = vq.reshape((-1,) + (1,) * (out.ndim - 1))
        out = jnp.squeeze(
            jnp.take_along_axis(out, idx, axis=q + 1), axis=q + 1
        )
    return out


def candidate_costs(graph, values):
    cand = graph.var_costs
    n_segments = graph.var_costs.shape[0]
    for bucket in graph.buckets:
        arity = bucket.var_ids.shape[1]
        for p in range(arity):
            fixed = _fix_other_axes(bucket.costs, bucket.var_ids, values, p)
            cand = cand + jax.ops.segment_sum(
                fixed, bucket.var_ids[:, p], num_segments=n_segments
            )
    return cand


def factor_current_costs(graph, values):
    out = []
    for bucket in graph.buckets:
        fixed = _fix_other_axes(bucket.costs, bucket.var_ids, values, 0)
        v0 = values[bucket.var_ids[:, 0]]
        out.append(jnp.take_along_axis(
            fixed, v0[:, None], axis=1
        ).squeeze(1))
    return tuple(out)


def assignment_cost(graph, values):
    total = jnp.sum(
        jnp.take_along_axis(
            graph.var_costs[:-1], values[:-1, None], axis=1
        )
    )
    for costs in factor_current_costs(graph, values):
        total = total + jnp.sum(costs)
    return total


def neighbor_max(graph, per_var):
    n_segments = graph.var_costs.shape[0]
    out = jnp.full((n_segments,), -jnp.inf, dtype=per_var.dtype)
    for bucket in graph.buckets:
        arity = bucket.var_ids.shape[1]
        for p in range(arity):
            for q in range(arity):
                if p == q:
                    continue
                vals_q = per_var[bucket.var_ids[:, q]]
                out = jnp.maximum(out, jax.ops.segment_max(
                    vals_q, bucket.var_ids[:, p],
                    num_segments=n_segments,
                ))
    return out


def neighbor_min_rank_where(graph, per_var, target, ranks):
    n_segments = graph.var_costs.shape[0]
    ranks = jnp.asarray(ranks, dtype=jnp.float32)
    out = jnp.full((n_segments,), jnp.inf, dtype=jnp.float32)
    for bucket in graph.buckets:
        arity = bucket.var_ids.shape[1]
        for p in range(arity):
            tgt_p = target[bucket.var_ids[:, p]]
            for q in range(arity):
                if p == q:
                    continue
                vq = bucket.var_ids[:, q]
                eligible = per_var[vq] == tgt_p
                cand_rank = jnp.where(eligible, ranks[vq], jnp.inf)
                out = jnp.minimum(out, jax.ops.segment_min(
                    cand_rank, bucket.var_ids[:, p],
                    num_segments=n_segments,
                ))
    return out


def neighborhood_winners(graph, cand, values, key, ranks):
    cur = jnp.take_along_axis(cand, values[:, None], axis=1).squeeze(1)
    best, is_best = best_candidates(graph, cand)
    improve = cur - best
    proposed = random_best_choice(key, is_best)
    nmax = neighbor_max(graph, improve)
    nrank = neighbor_min_rank_where(graph, improve, improve, ranks)
    wins = (improve > nmax) | ((improve == nmax) & (ranks < nrank))
    return improve, proposed, nmax, wins


def best_candidates(graph, cand):
    masked = jnp.where(graph.var_valid, cand, jnp.inf)
    best = jnp.min(masked, axis=1)
    return best, masked == best[:, None]


def random_best_choice(key, is_best):
    u = jax.random.uniform(key, is_best.shape)
    return jnp.argmax(jnp.where(is_best, u, -1.0), axis=1).astype(jnp.int32)


def random_initial_values(key, graph):
    u = jax.random.uniform(key, graph.var_valid.shape)
    return jnp.argmax(
        jnp.where(graph.var_valid, u, -1.0), axis=1
    ).astype(jnp.int32)


# ---- frozen DSA ------------------------------------------------------- #


class GoldenDsaState(NamedTuple):
    values: jnp.ndarray
    key: jnp.ndarray
    cycle: jnp.ndarray


def dsa_init(graph: CompiledFactorGraph, seed: int = 0) -> GoldenDsaState:
    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    return GoldenDsaState(
        values=random_initial_values(k0, graph),
        key=key,
        cycle=jnp.asarray(0, dtype=jnp.int32),
    )


def _factor_optima(graph):
    return tuple(
        jnp.min(b.costs, axis=tuple(range(1, b.costs.ndim)))
        for b in graph.buckets
    )


def violated_vars(graph, values):
    n_segments = graph.var_costs.shape[0]
    out = jnp.zeros((n_segments,), dtype=jnp.int32)
    for bucket, cur, opt in zip(
        graph.buckets, factor_current_costs(graph, values),
        _factor_optima(graph),
    ):
        viol = (cur != opt).astype(jnp.int32)
        for p in range(bucket.var_ids.shape[1]):
            out = jnp.maximum(out, jax.ops.segment_max(
                viol, bucket.var_ids[:, p], num_segments=n_segments
            ))
    return out > 0


def dsa_step(state, graph, *, variant, probability):
    key, k_choice, k_change = jax.random.split(state.key, 3)
    values = state.values

    cand = candidate_costs(graph, values)
    cur = jnp.take_along_axis(cand, values[:, None], axis=1).squeeze(1)
    best, is_best = best_candidates(graph, cand)
    delta = cur - best

    if variant == "A":
        eligible = delta > 0
        choice_mask = is_best
    else:
        n_best = jnp.sum(is_best, axis=1)
        one_hot_cur = (
            jnp.arange(cand.shape[1])[None, :] == values[:, None]
        )
        drop_cur = ((delta == 0) & (n_best > 1))[:, None] & one_hot_cur
        choice_mask = is_best & ~drop_cur
        if variant == "B":
            eligible = (delta > 0) | (
                (delta == 0) & violated_vars(graph, values)
            )
        else:  # C
            eligible = delta >= 0

    new_vals = random_best_choice(k_choice, choice_mask)
    u = jax.random.uniform(k_change, (values.shape[0],))
    change = eligible & (u < probability)
    values = jnp.where(change, new_vals, values)
    return GoldenDsaState(values=values, key=key, cycle=state.cycle + 1)


def run_dsa(graph, max_cycles, *, variant="B", probability=0.7, seed=0):
    state = dsa_init(graph, seed)
    state = jax.lax.fori_loop(
        0, max_cycles,
        lambda i, s: dsa_step(
            s, graph, variant=variant, probability=probability
        ),
        state,
    )
    cost = assignment_cost(graph, state.values)
    return state.values[:-1], cost, state.cycle


# ---- frozen MGM ------------------------------------------------------- #


class GoldenMgmState(NamedTuple):
    values: jnp.ndarray
    key: jnp.ndarray
    cycle: jnp.ndarray


def mgm_init(graph: CompiledFactorGraph, seed: int = 0) -> GoldenMgmState:
    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    return GoldenMgmState(
        values=random_initial_values(k0, graph),
        key=key,
        cycle=jnp.asarray(0, dtype=jnp.int32),
    )


def mgm_step(state, graph, *, lexic_ranks, break_mode):
    key, k_choice, k_rand = jax.random.split(state.key, 3)
    values = state.values

    if break_mode == "random":
        ranks = jax.random.uniform(k_rand, values.shape)
    else:
        ranks = lexic_ranks

    cand = candidate_costs(graph, values)
    gain, proposed, _, wins = neighborhood_winners(
        graph, cand, values, k_choice, ranks
    )
    new_vals = jnp.where(gain > 0, proposed, values)
    values = jnp.where(wins, new_vals, values)
    return GoldenMgmState(values=values, key=key, cycle=state.cycle + 1)


def run_mgm(graph, max_cycles, *, lexic_ranks, break_mode="lexic", seed=0):
    state = mgm_init(graph, seed)
    state = jax.lax.fori_loop(
        0, max_cycles,
        lambda i, s: mgm_step(
            s, graph, lexic_ranks=lexic_ranks, break_mode=break_mode
        ),
        state,
    )
    cost = assignment_cost(graph, state.values)
    return state.values[:-1], cost, state.cycle

"""Agent-mode runtime: message-passing computations on threaded agents.

Reference parity: pydcop/infrastructure/ — this is the reference's
execution model (one thread per agent, per-agent priority message queue,
central orchestrator), kept alongside the device engine for
reference-equivalent distributed execution, multi-machine deployment and
the resilience features (replication, repair, dynamic scenarios).
"""

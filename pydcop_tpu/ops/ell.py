"""The shared ELL (padded edge-list) gather-reduce primitive.

One definition of the clip+mask dense gather that both kernel families
aggregate through when a graph carries ``agg_ell`` (compile_dcop
(aggregation='ell'), engine/compile.build_aggregation_arrays):
MaxSum's belief aggregation (ops/maxsum.aggregate_beliefs) and the
local-search positional sums/reductions (ops/localsearch).

Dummy slots in the [V+1, K] lists hold E (one past the last edge);
the gather clips the index (a real, counted read — see
engine/roofline.maxsum_superstep_bytes) and the mask replaces the
value with the reduction's identity.  A zero-row append would be
simpler but copies the whole edge array every cycle.
"""

import jax.numpy as jnp


def gather_reduce(ell: jnp.ndarray, edge_vals: jnp.ndarray, fill,
                  reduce_fn) -> jnp.ndarray:
    """Reduce per-edge values into per-variable values through the
    ell lists.

    ``edge_vals`` is [E] or [E, D] in the flattened (bucket, factor,
    position) edge order the lists index; returns [V+1] or [V+1, D].
    ``fill`` is the identity of ``reduce_fn`` (0 for sum, -inf for
    max, +inf for min).
    """
    n_edges = edge_vals.shape[0]
    safe = jnp.minimum(ell, n_edges - 1)
    mask = ell < n_edges
    if edge_vals.ndim == 2:
        mask = mask[..., None]
    return reduce_fn(jnp.where(mask, edge_vals[safe], fill), axis=1)

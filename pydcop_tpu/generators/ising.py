"""Ising-model benchmark generator.

Reference parity: pydcop/commands/generators/ising.py (:274
generate_ising): toroidal grid of binary variables; binary constraint
between neighbors costs k when equal and -k when different with
k ~ U[-bin_range, bin_range] (:360-395); unary constraint per variable
costs r for 0 and -r for 1 with r ~ U[-un_range, un_range] (:397-420);
one agent per grid cell, with factor-graph or variable distributions.
"""

from typing import Dict, Optional, Tuple

import numpy as np

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import (
    NAryMatrixRelation,
    constraint_from_str,
)
from pydcop_tpu.generators.graphs import grid_2d_graph


def generate_ising(
    row_count: int,
    col_count: Optional[int] = None,
    bin_range: float = 1.6,
    un_range: float = 0.05,
    extensive: bool = True,
    no_agents: bool = False,
    fg_dist: bool = False,
    var_dist: bool = False,
    seed: Optional[int] = None,
) -> Tuple[DCOP, Dict, Dict]:
    """Returns (dcop, var_mapping, fg_mapping)."""
    if col_count is None:
        col_count = row_count
    rng = np.random.default_rng(seed)
    domain = Domain("var_domain", "binary", [0, 1])
    variables = {
        (r, c): Variable(f"v_{r}_{c}", domain)
        for r in range(row_count) for c in range(col_count)
    }
    dcop = DCOP(
        f"Ising_{row_count}_{col_count}_{bin_range}_{un_range}",
        objective="min",
    )
    for v in variables.values():
        dcop.add_variable(v)

    # Unary constraints.
    for (r, c), v in variables.items():
        value = float(rng.uniform(-un_range, un_range))
        name = f"cu_{v.name}"
        if extensive:
            dcop.add_constraint(NAryMatrixRelation(
                [v], np.array([value, -value]), name))
        else:
            dcop.add_constraint(constraint_from_str(
                name, f"{value} if {v.name} == 0 else {-value}", [v]))

    # Binary constraints on the toroidal grid.
    for (n1, n2) in grid_2d_graph(row_count, col_count, periodic=True):
        v1, v2 = variables[n1], variables[n2]
        value = float(rng.uniform(-bin_range, bin_range))
        name = f"cb_{v1.name}_{v2.name}"
        if extensive:
            table = np.array([[value, -value], [-value, value]])
            dcop.add_constraint(NAryMatrixRelation([v1, v2], table, name))
        else:
            dcop.add_constraint(constraint_from_str(
                name,
                f"{value} if {v1.name} == {v2.name} else {-value}",
                [v1, v2],
            ))

    var_mapping: Dict[str, list] = {}
    fg_mapping: Dict[str, list] = {}
    if not no_agents:
        for (r, c), v in variables.items():
            agent = AgentDef(f"a_{r}_{c}")
            dcop.add_agents(agent)
            if var_dist:
                var_mapping[agent.name] = [v.name]
            if fg_dist:
                fg_mapping[agent.name] = [v.name, f"cu_{v.name}"]
        if fg_dist:
            # Assign each binary constraint to exactly one agent (its
            # first endpoint's) — derived from the real edge list so
            # small/toroidal-duplicate grids stay consistent.
            for (n1, n2) in grid_2d_graph(
                row_count, col_count, periodic=True
            ):
                v1, v2 = variables[n1], variables[n2]
                fg_mapping[f"a_{n1[0]}_{n1[1]}"].append(
                    f"cb_{v1.name}_{v2.name}"
                )
    return dcop, var_mapping, fg_mapping

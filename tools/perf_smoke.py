"""Perf-smoke gate: the hot-path overhaul's measurable claims, on CPU.

Part of ``make test`` (like ``make chaos`` / ``make trace-demo``):
quick, deterministic checks that the compile fast paths actually stay
fast and the autotuner only makes valid choices —

1. **Vectorized compile**: compiling a 10k-binary-factor
   expression-constraint instance with the vectorized+memoized table
   evaluation must be >= 3x faster than the per-factor per-assignment
   reference loop (ISSUE 3 acceptance; measured ~5x on this box).
2. **Structure cache**: recompiling a same-structured problem must
   hit the layout cache — layout/agg-array construction skipped
   entirely (counter-asserted) and the warm compile faster than the
   cold one.
3. **Autotuner**: ``aggregation='auto'`` must pick one of the four
   named strategies (never "boundary" — numerics), record timings,
   and replay its decision from the JSON shape cache.
4. **Flight-recorder overhead** (ISSUE 9 acceptance): the always-on
   ring must cost <= 5% on the segmented-run benchmark — recorder
   attached vs detached on the same warmed engine, min-of-N runs
   (events reach the ring only at segment boundaries; the jitted
   loop itself is untouched).
5. **Efficiency-plane overhead** (ISSUE 14 acceptance): the device-
   efficiency accounting plane (per-dispatch attainment records,
   jit accounting) must cost <= 5% on the serving-shaped batched
   dispatch — tracker on vs off, PAIRWISE interleaved so CPU
   frequency drift and concurrent-load flake cannot masquerade as
   plane overhead.
6. **Cross-edge consistency** (ISSUE 17 acceptance): on a seeded
   low-width graph with soft-dominated domain values, the CEC
   preprocessing pass must either speed the warmed UTIL sweep by
   >= 1.2x or gain >= 1 effective width rung (one domain factor off
   the largest UTIL hypercube) — CEC-on vs CEC-off PAIRWISE
   interleaved — while the returned assignment stays bit-identical.
7. **Pipelined flushes** (ISSUE 18 acceptance): on a seeded 4-bin
   flush, the pipelined scheduler (launch k+1 while k's arrays are
   in flight) must return BIT-IDENTICAL assignments to the
   synchronous path and never cost more than 2% over it; where the
   box has a second core to overlap on (>= 2 CPUs) it must also be
   >= 1.15x faster — on/off PAIRWISE interleaved, min-of-N.

Run:  python tools/perf_smoke.py      (exit 0 = all claims hold)
"""

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from pydcop_tpu.dcop.objects import Domain, Variable  # noqa: E402
from pydcop_tpu.dcop.relations import (  # noqa: E402
    NAryMatrixRelation,
    constraint_from_str,
)
from pydcop_tpu.engine.compile import (  # noqa: E402
    AGGREGATIONS,
    compile_cache,
    compile_factor_graph,
)

N_VARS = 2_000
N_FACTORS = 10_000
MIN_SPEEDUP = 3.0


def build_instance(n_vars=N_VARS, n_factors=N_FACTORS, penalty=10):
    """10k binary *expression* constraints (the acceptance instance):
    per-edge intentional constraints exactly as the YAML/generator
    path produces them."""
    rng = np.random.default_rng(7)
    d = Domain("colors", "", [0, 1, 2])
    vs = [Variable(f"x{i}", d) for i in range(n_vars)]
    pairs = rng.integers(0, n_vars, size=(n_factors, 2))
    loop = pairs[:, 0] == pairs[:, 1]
    pairs[loop, 1] = (pairs[loop, 0] + 1) % n_vars
    cons = [
        constraint_from_str(
            f"c{i}", f"{penalty} if x{a} == x{b} else 0",
            [vs[a], vs[b]])
        for i, (a, b) in enumerate(pairs)
    ]
    return vs, cons


def check_vectorized_compile() -> dict:
    best = 0.0
    t_old = t_new = None
    for _ in range(2):  # one retry damps a noisy neighbor
        vs, cons = build_instance()
        t0 = time.perf_counter()
        g_old, _ = compile_factor_graph(
            vs, cons, vectorize=False, use_cache=False)
        t_old = time.perf_counter() - t0
        vs, cons = build_instance()  # fresh: no per-instance caches
        t0 = time.perf_counter()
        g_new, _ = compile_factor_graph(
            vs, cons, vectorize=True, use_cache=False)
        t_new = time.perf_counter() - t0
        for b_old, b_new in zip(g_old.buckets, g_new.buckets):
            np.testing.assert_array_equal(b_old.costs, b_new.costs)
        best = max(best, t_old / t_new)
        if best >= MIN_SPEEDUP:
            break
    assert best >= MIN_SPEEDUP, (
        f"vectorized compile only {best:.2f}x faster than the "
        f"per-factor loop (need >= {MIN_SPEEDUP}x): "
        f"{t_old * 1e3:.0f}ms -> {t_new * 1e3:.0f}ms")
    return {"per_factor_ms": round(t_old * 1e3, 1),
            "vectorized_ms": round(t_new * 1e3, 1),
            "speedup": round(best, 2)}


def build_matrix_instance(n_vars=4_000, n_factors=20_000, seed=0):
    """Extensional (table) constraints: table evaluation is nearly
    free here, so compile time is layout-weighted — the instance that
    makes the structure-cache's layout skip show up on the clock."""
    rng = np.random.default_rng(7)
    d = Domain("colors", "", [0, 1, 2])
    vs = [Variable(f"x{i}", d) for i in range(n_vars)]
    pairs = rng.integers(0, n_vars, size=(n_factors, 2))
    loop = pairs[:, 0] == pairs[:, 1]
    pairs[loop, 1] = (pairs[loop, 0] + 1) % n_vars
    tables = [np.random.default_rng(seed + i).random((3, 3))
              for i in range(4)]
    cons = [
        NAryMatrixRelation([vs[a], vs[b]], tables[i % 4], f"m{i}")
        for i, (a, b) in enumerate(pairs)
    ]
    return vs, cons


def check_structure_cache() -> dict:
    # Interleaved cold/warm pairs (each pair adjacent in time, so a
    # noisy neighbor hits both sides) + min-of-N: the warm compile
    # does strictly less work, so min-vs-min is the honest compare.
    t_cold, t_warm = [], []
    for i in range(3):
        compile_cache.clear()
        vs, cons = build_matrix_instance(seed=10 * i)
        t0 = time.perf_counter()
        compile_factor_graph(vs, cons, aggregation="ell")
        t_cold.append(time.perf_counter() - t0)
        stats = compile_cache.stats()
        assert stats == {"hits": 0, "misses": 1, "layout_builds": 1,
                         "entries": 1}, stats
        # Same structure, new cost tables (the serving pattern): the
        # hit must skip layout construction entirely.
        vs, cons = build_matrix_instance(seed=10 * i + 5)
        t0 = time.perf_counter()
        compile_factor_graph(vs, cons, aggregation="ell")
        t_warm.append(time.perf_counter() - t0)
        stats = compile_cache.stats()
        assert stats["hits"] == 1, stats
        assert stats["layout_builds"] == 1, (
            f"layout rebuilt on a structure-cache hit: {stats}")
    assert min(t_warm) < min(t_cold), (
        f"cached compile not faster: cold {min(t_cold) * 1e3:.0f}ms "
        f"vs warm {min(t_warm) * 1e3:.0f}ms")
    return {"cold_ms": round(min(t_cold) * 1e3, 1),
            "warm_ms": round(min(t_warm) * 1e3, 1),
            "stats": stats}


def check_autotuner() -> dict:
    from pydcop_tpu.engine.autotune import autotune_aggregation

    vs, cons = build_instance(n_vars=300, n_factors=900)
    graph, _ = compile_factor_graph(vs, cons, use_cache=False)
    with tempfile.TemporaryDirectory() as td:
        cache = os.path.join(td, "tune.json")
        info = autotune_aggregation(graph, cache_file=cache)
        assert info["aggregation"] in AGGREGATIONS, info
        assert info["aggregation"] != "boundary", (
            "autotuner selected the numerics-disqualified strategy")
        assert info["aggregation_source"] == "measured", info
        timed = [s for s, t in info["aggregation_timings_ms"].items()
                 if t is not None]
        assert {"scatter", "sorted", "ell"} <= set(timed), info
        replay = autotune_aggregation(graph, cache_file=cache)
        assert replay["aggregation_source"] == "cache", replay
        assert replay["aggregation"] == info["aggregation"]
    return {"choice": info["aggregation"],
            "timings_ms": info["aggregation_timings_ms"]}


# ------------------------------------------------------------------ #
# ISSUE 10 work-reduction gates: branch-and-bound pruning and
# decimation, both on ONE large-domain loopy instance — an effective
# 2-coloring embedded in D=128 domains (two near-zero unary slots
# shared by every variable, the rest expensive): plain MaxSum
# oscillates on the frustrated loops (the decimation regime) while the
# big unary spread keeps the per-factor survivor sets tiny (the
# pruning regime).

PRUNE_MIN_SPEEDUP = 1.3
DECIM_MAX_FRACTION = 0.70
WR_N_VARS = 200
WR_DOMAIN = 128
WR_EDGE_FACTOR = 1.6
WR_BUDGET_CYCLES = 300


def build_workreduction_graph(seed=3, noise=0.01):
    """Direct-array build (compile would dominate the gate) of the
    gate instance + a minimal meta for the engine: integer unary
    costs in [32, 400) except two zero slots, equality penalty 1,
    deterministic tie-break noise like engine.compile applies."""
    from pydcop_tpu.engine.compile import (
        BIG,
        CompiledFactorGraph,
        FactorBucket,
        FactorGraphMeta,
    )

    rng = np.random.default_rng(seed)
    v, d = WR_N_VARS, WR_DOMAIN
    f = int(v * WR_EDGE_FACTOR)
    var_ids = rng.integers(0, v, size=(f, 2)).astype(np.int32)
    loop = var_ids[:, 0] == var_ids[:, 1]
    var_ids[loop, 1] = (var_ids[loop, 0] + 1) % v
    costs = np.ascontiguousarray(np.broadcast_to(
        np.eye(d, dtype=np.float32), (f, d, d))).copy()
    var_costs = np.full((v + 1, d), BIG, np.float32)
    unary = rng.integers(32, 400, size=(v, d)).astype(np.float32)
    unary[:, 0] = 0.0
    unary[:, 1] = 0.0
    base = unary.copy()
    var_costs[:-1] = unary + (
        noise * rng.random((v, d))).astype(np.float32)
    var_valid = np.zeros((v + 1, d), bool)
    var_valid[:-1] = True
    graph = CompiledFactorGraph(
        var_costs=var_costs, var_valid=var_valid,
        buckets=(FactorBucket(costs, var_ids),))
    meta = FactorGraphMeta(
        var_names=tuple(f"v{i}" for i in range(v)),
        domains=tuple(tuple(range(d)) for _ in range(v)),
        factor_names=tuple(f"c{i}" for i in range(f)),
        bucket_sizes=(f,), mode="min", var_base_costs=base)
    return graph, meta


def _constraint_cost(graph, values: np.ndarray) -> float:
    ids = np.asarray(graph.buckets[0].var_ids)
    return float(np.sum(values[ids[:, 0]] == values[ids[:, 1]]))


def check_pruning() -> dict:
    """Branch-and-bound pruning: >= 1.3x superstep throughput on the
    fixed-budget (serving-shaped) run AND a bit-identical trajectory —
    every state leaf equal, not just the assignment."""
    from functools import partial

    import jax

    from pydcop_tpu.ops import maxsum as ops

    graph, _meta = build_workreduction_graph()
    g = jax.device_put(graph)
    fns = {
        prune: jax.jit(partial(
            ops.run_maxsum, max_cycles=WR_BUDGET_CYCLES,
            stop_on_convergence=False, prune=prune))
        for prune in (False, True)
    }
    outs = {p: jax.block_until_ready(fn(g)) for p, fn in fns.items()}
    for (ld, lp) in zip(jax.tree_util.tree_leaves(outs[False]),
                        jax.tree_util.tree_leaves(outs[True])):
        assert np.array_equal(np.asarray(ld), np.asarray(lp)), \
            "pruned trajectory diverged from dense (bit-parity)"

    best = 0.0
    t_d = t_p = None
    for _ in range(3):  # best-of-N attempts damp a noisy neighbor
        d_times, p_times = [], []
        for _rep in range(3):  # interleaved: equal noise exposure
            t0 = time.perf_counter()
            jax.block_until_ready(fns[False](g))
            d_times.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            jax.block_until_ready(fns[True](g))
            p_times.append(time.perf_counter() - t0)
        t_d, t_p = min(d_times), min(p_times)
        best = max(best, t_d / t_p)
        if best >= PRUNE_MIN_SPEEDUP:
            break
    assert best >= PRUNE_MIN_SPEEDUP, (
        f"pruning only {best:.2f}x over the dense superstep (need >= "
        f"{PRUNE_MIN_SPEEDUP}x): dense {t_d * 1e3:.0f}ms -> pruned "
        f"{t_p * 1e3:.0f}ms")
    return {"dense_ms": round(t_d * 1e3, 1),
            "pruned_ms": round(t_p * 1e3, 1),
            "speedup": round(best, 2)}


def check_decimation() -> dict:
    """Decimation: reach the reference cost in <= 70% of the baseline
    wall time on the same graph.  Reference = the decimated run's
    final constraint cost; baseline = plain MaxSum's wall to first
    reach it, censored at the full fixed budget when it never does
    (the anytime-comparison convention: the loser is charged the
    budget it actually burned)."""
    from functools import partial

    import jax

    from pydcop_tpu.engine.runner import DecimationPlan, MaxSumEngine
    from pydcop_tpu.ops import maxsum as ops

    graph, meta = build_workreduction_graph()
    g = jax.device_put(graph)
    plan = DecimationPlan(frac_per_round=0.2, cycles_per_round=25)

    def decim_engine():
        return MaxSumEngine(graph, meta, prune=True)

    def decim_run(engine):
        t0 = time.perf_counter()
        res = engine.run_checkpointed(
            max_cycles=4 * WR_BUDGET_CYCLES,
            segment_cycles=plan.cycles_per_round,
            decimation=plan)
        return time.perf_counter() - t0, res

    engine = decim_engine()
    decim_run(engine)  # warm every jitted round + the margin fn
    ratio = float("inf")
    decim_s = base_s = ref = None
    plain_curve = None
    fn = jax.jit(partial(
        ops.run_maxsum, max_cycles=WR_BUDGET_CYCLES,
        stop_on_convergence=False))
    jax.block_until_ready(fn(g))  # warm the baseline program
    trace_fn = jax.jit(partial(
        ops.run_maxsum_trace, max_cycles=WR_BUDGET_CYCLES,
        stop_on_convergence=False))
    _st, _vv, plain_curve = jax.device_get(
        jax.block_until_ready(trace_fn(g)))
    plain_curve = np.asarray(plain_curve)
    for _ in range(3):
        d_s, res = decim_run(engine)
        values = np.array(
            [res.assignment[n] for n in meta.var_names])
        ref = _constraint_cost(graph, values)
        assert res.metrics["decimated_vars"] == WR_N_VARS
        # Plain wall to the reference cost, censored at the budget.
        budget_times = []
        for _rep in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(g))
            budget_times.append(time.perf_counter() - t0)
        budget_s = min(budget_times)
        below = np.nonzero(plain_curve <= ref)[0]
        frac = ((int(below[0]) + 1) / WR_BUDGET_CYCLES
                if below.size else 1.0)
        base_s = budget_s * frac
        decim_s = d_s
        ratio = min(ratio, decim_s / base_s)
        if ratio <= DECIM_MAX_FRACTION:
            break
    assert ratio <= DECIM_MAX_FRACTION, (
        f"decimation took {ratio:.0%} of the baseline wall to the "
        f"reference cost (budget {DECIM_MAX_FRACTION:.0%}): decim "
        f"{decim_s * 1e3:.0f}ms vs baseline {base_s * 1e3:.0f}ms "
        f"(ref cost {ref})")
    return {"decim_ms": round(decim_s * 1e3, 1),
            "baseline_ms": round(base_s * 1e3, 1),
            "fraction": round(ratio, 3),
            "ref_cost": ref,
            "plain_best_cost": float(plain_curve.min())}


MAX_FLIGHT_OVERHEAD = 1.05  # on/off runtime ratio (<= 5%)


def check_flight_overhead() -> dict:
    """The ISSUE 9 perf gate: an attached flight ring may cost at
    most 5% on the segmented-run benchmark.  Ring appends happen only
    at segment boundaries (the jitted loop never sees the recorder),
    so the measured ratio is noise-dominated — min-of-N per side,
    best-of-3 attempts, exactly like the compile checks above."""
    from pydcop_tpu.algorithms.maxsum import build_engine
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.observability.flight import FlightRecorder
    from pydcop_tpu.observability.trace import tracer

    rng = np.random.default_rng(7)
    d = Domain("c", "", [0, 1, 2])
    dcop = DCOP("flight_bench", objective="min")
    vs = [Variable(f"v{i}", d) for i in range(12)]
    for v in vs:
        dcop.add_variable(v)
    for k in range(12):
        dcop.add_constraint(NAryMatrixRelation(
            [vs[k], vs[(k + 1) % 12]],
            rng.integers(0, 10, size=(3, 3)).astype(float),
            f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    engine = build_engine(dcop, {})
    kw = dict(max_cycles=600, segment_cycles=5,
              stop_on_convergence=False)
    prev = tracer.flight
    tracer.set_flight(None)

    def timed() -> float:
        t0 = time.perf_counter()
        engine.run_checkpointed(**kw)
        return time.perf_counter() - t0

    try:
        timed()  # warm the jit cache once, outside the clock
        ratio = float("inf")
        t_off = t_on = None
        # Bundle dir never written on the happy path: ring only.
        ring = FlightRecorder(events=2048)
        for _ in range(4):
            offs, ons = [], []
            # Interleave off/on runs pairwise: a phase of all-off
            # followed by a phase of all-on lets CPU frequency drift
            # masquerade as recorder overhead; alternating gives both
            # sides the same noise exposure, min-of-N filters upward
            # excursions.
            for _rep in range(5):
                tracer.set_flight(None)
                offs.append(timed())
                tracer.set_flight(ring)
                ons.append(timed())
            tracer.set_flight(None)
            t_off, t_on = min(offs), min(ons)
            ratio = min(ratio, t_on / t_off)
            if ratio <= MAX_FLIGHT_OVERHEAD:
                break
    finally:
        tracer.set_flight(prev)
    assert ratio <= MAX_FLIGHT_OVERHEAD, (
        f"flight recorder costs {(ratio - 1) * 100:.1f}% on the "
        f"segmented run (budget {(MAX_FLIGHT_OVERHEAD - 1) * 100:.0f}"
        f"%): off {t_off * 1e3:.0f}ms -> on {t_on * 1e3:.0f}ms")
    return {"off_ms": round(t_off * 1e3, 1),
            "on_ms": round(t_on * 1e3, 1),
            "overhead": round(ratio - 1, 4)}


MAX_EFFICIENCY_OVERHEAD = 1.05  # on/off runtime ratio (<= 5%)


def check_efficiency_overhead() -> dict:
    """The ISSUE 14 perf gate: the efficiency accounting plane
    (observability/efficiency.py — per-dispatch attainment records +
    jit accounting) may cost at most 5% on the serving-shaped batched
    dispatch.  Recording is one lock + dict arithmetic per DISPATCH
    (milliseconds of device work), so the measured ratio is
    noise-dominated: off/on runs interleave PAIRWISE (a phase of
    all-off followed by all-on lets CPU frequency drift masquerade as
    plane overhead — the PR-9 methodology), min-of-N per side,
    best-of-attempts."""
    import jax

    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.engine import batch as engine_batch
    from pydcop_tpu.engine.compile import compile_dcop
    from pydcop_tpu.observability.efficiency import tracker
    from pydcop_tpu.observability.metrics import registry

    rng = np.random.default_rng(11)
    d = Domain("c", "", [0, 1, 2])
    dcop = DCOP("eff_bench", objective="min")
    vs = [Variable(f"v{i}", d) for i in range(16)]
    for v in vs:
        dcop.add_variable(v)
    for k in range(16):
        dcop.add_constraint(NAryMatrixRelation(
            [vs[k], vs[(k + 1) % 16]],
            rng.integers(0, 10, size=(3, 3)).astype(float),
            f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    graph, _meta = compile_dcop(dcop)
    graphs = [graph] * 4
    kw = dict(max_cycles=200, pad_to_bins=(4,))

    def timed() -> float:
        t0 = time.perf_counter()
        for _ in range(4):
            engine_batch.run_stacked(graphs, **kw)
        return time.perf_counter() - t0

    was_enabled = tracker.enabled
    was_active = registry.active
    registry.active = True  # the serving posture: export paths live
    try:
        tracker.enabled = True
        timed()  # warm the jit cache once, outside the clock
        jax.block_until_ready(jax.numpy.zeros(()))
        ratio = float("inf")
        t_off = t_on = None
        for _ in range(4):
            offs, ons = [], []
            for _rep in range(5):
                tracker.enabled = False
                offs.append(timed())
                tracker.enabled = True
                ons.append(timed())
            t_off, t_on = min(offs), min(ons)
            ratio = min(ratio, t_on / t_off)
            if ratio <= MAX_EFFICIENCY_OVERHEAD:
                break
    finally:
        tracker.enabled = was_enabled
        registry.active = was_active
    assert ratio <= MAX_EFFICIENCY_OVERHEAD, (
        f"efficiency plane costs {(ratio - 1) * 100:.1f}% on the "
        f"batched dispatch (budget "
        f"{(MAX_EFFICIENCY_OVERHEAD - 1) * 100:.0f}%): off "
        f"{t_off * 1e3:.0f}ms -> on {t_on * 1e3:.0f}ms")
    return {"off_ms": round(t_off * 1e3, 1),
            "on_ms": round(t_on * 1e3, 1),
            "overhead": round(ratio - 1, 4)}


MAX_NETFAULT_OVERHEAD = 1.02  # on/off runtime ratio (<= 2%)


def check_netfault_overhead() -> dict:
    """The ISSUE 19 perf gate: with a fault plan installed but no
    clause matching the live links, the seam's per-call plan scan may
    cost at most 2% on the serving hop — and that hop is the
    dedupe-enabled one (a caller-supplied ``request_id`` on every
    POST, the idempotent-forwarding wire shape, answered by the
    worker's early dedupe lookup).  Both sides route through
    ``netfault.exchange``; the off side has no plan (the production
    default: one ``plan()`` read), the on side scans clauses and a
    partition that match nothing.  Per-hop work is a socket round
    trip plus a dict hit, so the ratio is noise-dominated: pairwise-
    interleaved off/on reps, min-of-N per side, best-of-attempts —
    the PR-9 methodology."""
    from urllib.parse import urlsplit

    from pydcop_tpu import api
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.serving import netfault

    rng = np.random.default_rng(19)
    d = Domain("c", "", [0, 1, 2])
    dcop = DCOP("netfault_bench", objective="min")
    vs = [Variable(f"v{i}", d) for i in range(4)]
    for v in vs:
        dcop.add_variable(v)
    for k in range(3):
        dcop.add_constraint(NAryMatrixRelation(
            [vs[k], vs[k + 1]],
            rng.integers(0, 10, size=(3, 3)).astype(float),
            f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    body = json.dumps({
        "dcop": dcop_yaml(dcop),
        "params": {"max_cycles": 50},
        "request_id": "perf-netfault",
    }).encode()
    # Clauses/partition that match nothing on the measured link: the
    # scan runs in full on every hop, injects nothing.
    inactive = netfault.FaultPlan.parse(
        "seed=5;link=*>replica-*,drop=1.0,delay_ms=5;"
        "link=*>*,path=/no-such-endpoint,blackhole=1;"
        "partition=ghost-a/ghost-b")

    handle = api.serve(port=0)
    try:
        parts = urlsplit(handle.url)
        host, port = parts.hostname, parts.port

        def hop() -> None:
            status, _ctype, _payload = netfault.exchange(
                "perf-client", "worker-perf", host, port,
                "POST", "/solve", body=body, timeout=30.0)
            assert status == 202, f"solve hop answered {status}"

        def timed() -> float:
            t0 = time.perf_counter()
            for _ in range(40):
                hop()
            return time.perf_counter() - t0

        netfault.clear()
        hop()   # first delivery executes; every later hop dedupes
        timed()  # warm the server/socket path, outside the clock
        ratio = float("inf")
        t_off = t_on = None
        for _ in range(4):
            offs, ons = [], []
            for _rep in range(5):
                netfault.clear()
                offs.append(timed())
                netfault.install(inactive)
                ons.append(timed())
            netfault.clear()
            t_off, t_on = min(offs), min(ons)
            ratio = min(ratio, t_on / t_off)
            if ratio <= MAX_NETFAULT_OVERHEAD:
                break
        assert inactive.injected() == {}, (
            f"'inactive' plan injected faults: {inactive.injected()}")
    finally:
        netfault.clear()
        handle.stop()
    assert ratio <= MAX_NETFAULT_OVERHEAD, (
        f"inactive netfault plan costs {(ratio - 1) * 100:.1f}% on "
        f"the dedupe-enabled serving hop (budget "
        f"{(MAX_NETFAULT_OVERHEAD - 1) * 100:.0f}%): off "
        f"{t_off * 1e3:.0f}ms -> on {t_on * 1e3:.0f}ms")
    return {"off_ms": round(t_off * 1e3, 1),
            "on_ms": round(t_on * 1e3, 1),
            "overhead": round(ratio - 1, 4)}


MAX_FLEETTRACE_OVERHEAD = 1.02  # on/off runtime ratio (<= 2%)


def check_fleettrace_overhead() -> dict:
    """The ISSUE 20 perf gate: fleet tracing ON (context minting,
    header stamping, route-pick/retry instants, the flight tap and
    span shipping on both sides) may cost at most 2% on the routed
    serving hop versus ``PYDCOP_FLEET_TRACE=0``.  The toggle is
    ``FleetRouter.set_fleet_trace`` — the same env-knob flip + worker
    config push an operator gets — so both sides of the pair run the
    honest production path.  Noise discipline is the PR-9 methodology:
    pairwise-interleaved off/on batches, min-of-N per side,
    best-of-attempts, early exit once the budget holds.

    Rider invariant: with tracing ON the pooled ``/fleet/profile``
    ledger must still sum — telemetry that breaks the efficiency
    accounting is worse than no telemetry."""
    from urllib.parse import urlsplit

    from pydcop_tpu import api
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.serving import netfault

    rng = np.random.default_rng(20)
    d = Domain("c", "", [0, 1, 2])
    dcop = DCOP("fleettrace_bench", objective="min")
    vs = [Variable(f"v{i}", d) for i in range(4)]
    for v in vs:
        dcop.add_variable(v)
    for k in range(3):
        dcop.add_constraint(NAryMatrixRelation(
            [vs[k], vs[k + 1]],
            rng.integers(0, 10, size=(3, 3)).astype(float),
            f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    body = json.dumps({
        "dcop": dcop_yaml(dcop),
        "params": {"max_cycles": 50},
        "wait": True,
    }).encode()

    handle = api.serve(port=0, replicas=2, batch_window_s=0.01,
                       heartbeat_s=0.25)
    try:
        router = handle.router
        parts = urlsplit(handle.url)
        host, port = parts.hostname, parts.port

        def hop() -> None:
            status, _ctype, _payload = netfault.exchange(
                "perf-client", "router", host, port,
                "POST", "/solve", body=body, timeout=60.0)
            assert status in (200, 202), \
                f"routed solve hop answered {status}"

        def timed() -> float:
            t0 = time.perf_counter()
            for _ in range(20):
                hop()
            return time.perf_counter() - t0

        router.set_fleet_trace(True)
        hop()    # compile the structure on first delivery
        timed()  # warm the routed socket path, outside the clock
        ratio = float("inf")
        t_off = t_on = None
        for _ in range(4):
            offs, ons = [], []
            for _rep in range(4):
                router.set_fleet_trace(False)
                offs.append(timed())
                router.set_fleet_trace(True)
                ons.append(timed())
            t_off, t_on = min(offs), min(ons)
            ratio = min(ratio, t_on / t_off)
            if ratio <= MAX_FLEETTRACE_OVERHEAD:
                break

        status, _ctype, payload = netfault.exchange(
            "perf-client", "router", host, port,
            "GET", "/fleet/profile", timeout=30.0)
        assert status == 200, f"/fleet/profile answered {status}"
        ledger = json.loads(payload)["ledger"]
        total = max(float(ledger.get("total_s") or 0.0), 1e-9)
        unacct = abs(float(ledger.get("unaccounted_abs_s") or 0.0))
        assert unacct <= 0.05 * total, (
            f"pooled ledger no longer sums with tracing on: "
            f"|unaccounted| {unacct:.4f}s > 5% of {total:.4f}s")
    finally:
        handle.stop()
    assert ratio <= MAX_FLEETTRACE_OVERHEAD, (
        f"fleet tracing costs {(ratio - 1) * 100:.1f}% on the routed "
        f"serving hop (budget "
        f"{(MAX_FLEETTRACE_OVERHEAD - 1) * 100:.0f}%): off "
        f"{t_off * 1e3:.0f}ms -> on {t_on * 1e3:.0f}ms")
    return {"off_ms": round(t_off * 1e3, 1),
            "on_ms": round(t_on * 1e3, 1),
            "overhead": round(ratio - 1, 4),
            "ledger_unaccounted_s": round(unacct, 4)}


CEC_MIN_SPEEDUP = 1.2
CEC_N_VARS = 60
CEC_DOMAIN = 8


def build_cec_graph(seed=17, n=CEC_N_VARS, d=CEC_DOMAIN):
    """Seeded low-width instance where CEC provably bites: a banded
    chain whose factor tables carry a +10 offset on the upper half of
    every domain (``m[a][b] = base + off[a] + off[b]``), so those
    values are soft-dominated from every context and the consistency
    pass halves each hypercube axis."""
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable

    rng = np.random.default_rng(seed)
    dom = Domain("c", "", list(range(d)))
    dcop = DCOP("cec_bench", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    off = np.where(np.arange(d) < d // 2, 0.0, 10.0)
    k = 0
    for i in range(1, n):
        for j in (i - 1, i - 2):
            if j < 0:
                continue
            table = (rng.random((d, d))
                     + off[:, None] + off[None, :])
            dcop.add_constraint(
                NAryMatrixRelation([vs[j], vs[i]], table, f"c{k}"))
            k += 1
    dcop.add_agents([AgentDef("a0")])
    return dcop


def check_cec() -> dict:
    """The ISSUE 17 perf gate: CEC preprocessing must pay for itself
    on the UTIL sweep.  Both engines are warmed (compiles and the
    one-shot dominance pass land outside the clock — serving and the
    portfolio race reuse cached survivors the same way), then CEC-off
    and CEC-on sweeps interleave PAIRWISE (the PR-9 methodology),
    min-of-N per side.  Pass = >= 1.2x sweep throughput OR >= 1
    effective width rung gained; bit-identical assignment always."""
    import math

    from pydcop_tpu.computations_graph import pseudotree as pt
    from pydcop_tpu.engine.dpop import DpopEngine
    from pydcop_tpu.ops.dpop import cec_survivors, tree_stats

    dcop = build_cec_graph()
    tree = pt.build_computation_graph(dcop)
    survivors, meta = cec_survivors(tree, "min")
    assert meta["pruned"] > 0, (
        "CEC pruned nothing on the dominated-value instance "
        f"({meta})")
    raw = tree_stats(tree)["max_elements"]
    shrunk = tree_stats(tree, survivors)["max_elements"]
    # One rung = one domain factor off the largest hypercube: the
    # width-ceiling currency (a problem one rung smaller admits one
    # more separator variable at the same element cap).
    rungs = (math.log(raw / shrunk, CEC_DOMAIN) if shrunk else 0.0)

    on = DpopEngine(tree, mode="min", cec=True)
    off = DpopEngine(tree, mode="min", cec=False)
    res_on = on.run()    # warm: compiles + survivor cache
    res_off = off.run()
    assert res_on.assignment == res_off.assignment, (
        "CEC-on assignment diverged from CEC-off")
    ratio = 0.0
    t_on = t_off = None
    for _ in range(3):  # best-of-attempts damps a noisy neighbor
        offs, ons = [], []
        for _rep in range(4):  # pairwise interleaved
            t0 = time.perf_counter()
            off.run()
            offs.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            on.run()
            ons.append(time.perf_counter() - t0)
        t_off, t_on = min(offs), min(ons)
        ratio = max(ratio, t_off / t_on)
        if ratio >= CEC_MIN_SPEEDUP:
            break
    assert ratio >= CEC_MIN_SPEEDUP or rungs >= 1.0, (
        f"CEC gained only {ratio:.2f}x sweep throughput (need >= "
        f"{CEC_MIN_SPEEDUP}x) and {rungs:.2f} width rungs (need >= "
        f"1): off {t_off * 1e3:.1f}ms -> on {t_on * 1e3:.1f}ms, "
        f"max_elements {raw} -> {shrunk}")
    return {"off_ms": round(t_off * 1e3, 2),
            "on_ms": round(t_on * 1e3, 2),
            "speedup": round(ratio, 2),
            "pruned_values": meta["pruned"],
            "max_elements_raw": raw,
            "max_elements_cec": shrunk,
            "width_rungs_gained": round(rungs, 2)}


PIPELINE_MIN_SPEEDUP = 1.15       # hard gate only with >= 2 CPUs
PIPELINE_MAX_DISABLED_OVERHEAD = 1.02  # on/off wall ratio, always


def check_pipelining() -> dict:
    """The ISSUE 18 perf gate: the pipelined flush (scheduler
    launches bin k+1's device call while bin k's arrays are still in
    flight, decode drained in pickup order) must give BIT-IDENTICAL
    assignments to the synchronous path, cost <= 2% when the overlap
    cannot help, and — where a second core exists to overlap decode
    with execute — run the seeded 4-bin flush >= 1.15x faster.
    On/off runs interleave PAIRWISE (the PR-9 methodology), min-of-N
    per side, best-of-attempts."""
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef
    from pydcop_tpu.serving.service import SolveService

    def ring(n, seed, d=3):
        rng = np.random.default_rng(seed)
        dom = Domain("c", "", list(range(d)))
        dcop = DCOP(f"pipe_ring{n}_{seed}", objective="min")
        vs = [Variable(f"v{i}", dom) for i in range(n)]
        for v in vs:
            dcop.add_variable(v)
        for k in range(n):
            table = rng.integers(0, 10, size=(d, d)).astype(float)
            dcop.add_constraint(NAryMatrixRelation(
                [vs[k], vs[(k + 1) % n]], table, f"c{k}"))
        dcop.add_agents([AgentDef("a0")])
        return dcop

    # Four structure bins, two requests each: one flush, four
    # pipelined device dispatches.  Cycle count high enough that
    # device work dominates the fixed batch window on both sides.
    dcops = [ring(n, seed)
             for n in (17, 18, 19, 20) for seed in (0, 1)]
    params = {"max_cycles": 2000}

    def burst(service):
        t0 = time.perf_counter()
        ids = [service.submit(d, params=params) for d in dcops]
        res = [service.result(i, wait=120) for i in ids]
        wall = time.perf_counter() - t0
        assert all(r["status"] == "FINISHED" for r in res), res
        return wall, [tuple(sorted(r["assignment"].items()))
                      for r in res]

    on = SolveService(batch_window_s=0.04, max_batch=16,
                      pipeline=True, speculate=False).start()
    off = SolveService(batch_window_s=0.04, max_batch=16,
                       pipeline=False, speculate=False).start()
    try:
        # Warm pass on each side: compiles land outside the clock
        # (the jit cache is process-wide, so one side's warmup warms
        # both — run both anyway so either order is safe).
        _, baseline = burst(off)
        _, warm_on = burst(on)
        assert warm_on == baseline, (
            "pipelined flush diverged from synchronous assignments")
        assert on.pipelined_dispatches > 0, (
            "pipeline=True service never actually pipelined")
        assert off.pipelined_dispatches == 0, (
            "pipeline=False service pipelined anyway")
        overhead = float("inf")
        speedup = 0.0
        t_off = t_on = None
        multicore = (os.cpu_count() or 1) >= 2
        for _ in range(4):  # best-of-attempts damps noisy neighbors
            offs, ons = [], []
            for _rep in range(3):  # pairwise interleaved
                wall, got = burst(off)
                assert got == baseline
                offs.append(wall)
                wall, got = burst(on)
                assert got == baseline
                ons.append(wall)
            t_off, t_on = min(offs), min(ons)
            overhead = min(overhead, t_on / t_off)
            speedup = max(speedup, t_off / t_on)
            if overhead <= PIPELINE_MAX_DISABLED_OVERHEAD and (
                    speedup >= PIPELINE_MIN_SPEEDUP
                    or not multicore):
                break
    finally:
        on.stop()
        off.stop()
    assert overhead <= PIPELINE_MAX_DISABLED_OVERHEAD, (
        f"pipelined flush costs {(overhead - 1) * 100:.1f}% over the "
        f"synchronous path (budget "
        f"{(PIPELINE_MAX_DISABLED_OVERHEAD - 1) * 100:.0f}%): off "
        f"{t_off * 1e3:.0f}ms -> on {t_on * 1e3:.0f}ms")
    if multicore:
        # One core cannot overlap decode with execute — the speedup
        # claim is only falsifiable with a second one.
        assert speedup >= PIPELINE_MIN_SPEEDUP, (
            f"pipelined flush gained only {speedup:.2f}x (need >= "
            f"{PIPELINE_MIN_SPEEDUP}x on a multicore box): off "
            f"{t_off * 1e3:.0f}ms -> on {t_on * 1e3:.0f}ms")
    return {"off_ms": round(t_off * 1e3, 1),
            "on_ms": round(t_on * 1e3, 1),
            "speedup": round(speedup, 3),
            "speedup_gated": multicore}


def main() -> int:
    results = {}
    for name, check in (
        ("vectorized_compile", check_vectorized_compile),
        ("structure_cache", check_structure_cache),
        ("autotuner", check_autotuner),
        ("pruning", check_pruning),
        ("decimation", check_decimation),
        ("flight_overhead", check_flight_overhead),
        ("efficiency_overhead", check_efficiency_overhead),
        ("netfault_overhead", check_netfault_overhead),
        ("fleettrace_overhead", check_fleettrace_overhead),
        ("cec", check_cec),
        ("pipelining", check_pipelining),
    ):
        try:
            results[name] = check()
        except AssertionError as e:
            print(f"perf-smoke: {name} FAILED: {e}")
            return 1
    print("perf-smoke: all checks passed")
    print(json.dumps(results))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""SECP sharded benchmark — BASELINE config #5: smart-lighting-style
factor population (default 100k binary rule factors over 4k lights,
domain 5) compiled, sharded over every available device, solved with
the MaxSum engine; reports iters/s, per-device memory, and final cost.

On a real multi-chip TPU slice the mesh rides ICI; under
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu it
exercises the identical sharded program on the virtual mesh (what
tests/api/test_secp_sharded_scale.py asserts bit-parity for).

Run: python benchmarks/bench_secp_sharded.py [n_rules]
Prints one JSON line.
"""

import json
import sys
import time

import numpy as np

N_LIGHTS = 4_000
N_RULES = 100_000
D = 5
CYCLES = 50


def build_arrays(n_lights, n_rules, seed=0):
    """SECP rule tables as device-ready arrays (building 100k Python
    constraint objects adds minutes of host time for no benchmark
    signal; the structure matches the generator's rule factors:
    |li - ti| + |lj - tj| over light pairs)."""
    rng = np.random.default_rng(seed)
    pairs = rng.integers(0, n_lights, size=(n_rules, 2)).astype(np.int32)
    # No self-loop factors (the generator pairs DISTINCT lights,
    # rng.choice replace=False): resample the second slot on collision.
    loops = pairs[:, 0] == pairs[:, 1]
    while loops.any():
        pairs[loops, 1] = rng.integers(
            0, n_lights, size=int(loops.sum()))
        loops = pairs[:, 0] == pairs[:, 1]
    ti = rng.integers(0, D, size=n_rules)
    tj = rng.integers(0, D, size=n_rules)
    grid = np.arange(D)
    tables = (
        np.abs(grid[None, :, None] - ti[:, None, None])
        + np.abs(grid[None, None, :] - tj[:, None, None])
    ).astype(np.float32)
    return pairs, tables


def main():
    from pydcop_tpu.utils.cleanenv import ensure_live_backend

    ensure_live_backend(tag="bench_secp_sharded")
    n_rules = int(sys.argv[1]) if len(sys.argv) > 1 else N_RULES
    import jax

    from pydcop_tpu.engine.compile import (
        BIG,
        CompiledFactorGraph,
        FactorBucket,
    )
    from pydcop_tpu.engine.sharding import make_mesh, shard_graph
    from pydcop_tpu.ops import maxsum as ops

    n_devices = len(jax.devices())
    pairs, tables = build_arrays(N_LIGHTS, n_rules)
    # Pad rows to divide the mesh (sentinel var id = N_LIGHTS).
    pad = (-n_rules) % max(n_devices, 1)
    if pad:
        pairs = np.concatenate(
            [pairs, np.full((pad, 2), N_LIGHTS, np.int32)])
        tables = np.concatenate(
            [tables, np.zeros((pad, D, D), np.float32)])
    var_costs = np.full((N_LIGHTS + 1, D), BIG, np.float32)
    var_costs[:-1] = np.random.default_rng(1).random(
        (N_LIGHTS, D)) * 0.01
    var_valid = np.zeros((N_LIGHTS + 1, D), bool)
    var_valid[:-1] = True
    graph = CompiledFactorGraph(
        var_costs=var_costs, var_valid=var_valid,
        buckets=(FactorBucket(tables, pairs),),
    )

    bucket_bytes = sum(
        b.costs.nbytes + b.var_ids.nbytes for b in graph.buckets)
    replicated = graph.var_costs.nbytes + graph.var_valid.nbytes
    per_device_mb = (bucket_bytes / n_devices + replicated) / 1e6

    if n_devices > 1:
        mesh = make_mesh(n_devices)
        graph = shard_graph(graph, mesh)
    else:
        graph = jax.device_put(graph)

    from functools import partial

    from pydcop_tpu.engine.timing import timed_call

    # timed_call forces true completion via a host fetch —
    # block_until_ready is a partial sync on the axon TPU tunnel
    # (engine/timing.py), which would turn both windows into enqueue
    # times if this bench ever runs on real hardware.
    fn = jax.jit(partial(ops.run_maxsum, max_cycles=CYCLES,
                         stop_on_convergence=False))
    _, compile_s = timed_call(fn, graph)
    (state, values), elapsed = timed_call(fn, graph)

    final_cost = float(ops.assignment_constraint_cost(graph, values))
    print(json.dumps({
        "metric": "secp_sharded_cycles_per_sec",
        "value": round(int(state.cycle) / elapsed, 2),
        "unit": "cycles/s",
        "n_rules": n_rules,
        "n_lights": N_LIGHTS,
        "n_devices": n_devices,
        "backend": jax.devices()[0].platform,
        "per_device_mb": round(per_device_mb, 1),
        "compile_s": round(compile_s, 2),
        "final_cost": round(final_cost, 1),
    }))


if __name__ == "__main__":
    main()

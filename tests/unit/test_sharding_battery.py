"""Battery for ISSUE 7: min-edge-cut partitioning, the partitioned
shard_map engine's plumbing, per-shard trace lanes, and the sharded
bench sentinel series.

End-to-end sharded-vs-single parity lives in
tests/api/test_sharded_parity.py; this battery covers the host-side
pieces (partitioner invariants, cache, communication accounting,
merge-lane separation, sentinel) plus kernel edge cases (mixed
arity, constraint-free graphs) that the api battery's problem
generators don't reach.
"""

import json
import os
import sys

import numpy as np
import pytest

import jax

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(REPO, "tools"))

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.engine.compile import compile_dcop
from pydcop_tpu.engine.partition import (
    Partition,
    build_adjacency,
    cut_statistics,
    partition_cache,
    partition_compiled,
    partition_factor_graph,
)

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
)


def _grid_scopes(side):
    """Scope-index array of a 4-neighbor grid (one binary bucket)."""
    edges = []
    for r in range(side):
        for c in range(side):
            i = r * side + c
            if r + 1 < side:
                edges.append((i, (r + 1) * side + c))
            if c + 1 < side:
                edges.append((i, r * side + c + 1))
    return [np.asarray(edges, np.int64)], side * side


def _grid_dcop(side=8, seed=0):
    """Shared 4-neighbor grid-coloring builder (bench.build_grid_dcop
    — the same instance family the bench and shard-smoke measure)."""
    from bench import build_grid_dcop

    return build_grid_dcop(side, seed=seed)


# ------------------------------ partitioner ------------------------- #


class TestPartitioner:
    def test_every_variable_assigned_once(self):
        scopes, n = _grid_scopes(12)
        part = partition_factor_graph(scopes, n, 8)
        assert part.var_shard.shape == (n,)
        assert part.var_shard.min() >= 0
        assert part.var_shard.max() <= 7
        assert sum(part.stats["owned_vars_per_shard"]) == n

    def test_balance_within_cap(self):
        scopes, n = _grid_scopes(12)
        part = partition_factor_graph(scopes, n, 8, imbalance=0.1)
        # The cap is integral: no shard may own more than
        # ceil(V/S * (1 + imbalance)) variables.
        cap = int(np.ceil(n / 8 * 1.1))
        assert max(part.stats["owned_vars_per_shard"]) <= cap

    def test_grid_cut_is_small(self):
        """The acceptance regime: a locally-connected loopy graph
        partitions with edge_cut_fraction < 0.3 (grids measure far
        below that — this is the honest floor, not the target)."""
        scopes, n = _grid_scopes(16)
        part = partition_factor_graph(scopes, n, 8)
        assert part.stats["edge_cut_fraction"] < 0.3

    def test_deterministic(self):
        scopes, n = _grid_scopes(10)
        a = partition_factor_graph(scopes, n, 4)
        b = partition_factor_graph(scopes, n, 4)
        assert np.array_equal(a.var_shard, b.var_shard)
        for fa, fb in zip(a.factor_shard, b.factor_shard):
            assert np.array_equal(fa, fb)

    def test_refinement_never_hurts(self):
        scopes, n = _grid_scopes(14)
        raw = partition_factor_graph(scopes, n, 8, refine_passes=0)
        refined = partition_factor_graph(scopes, n, 8, refine_passes=4)
        assert (refined.stats["edge_cut_fraction"]
                <= raw.stats["edge_cut_fraction"] + 1e-12)

    def test_factor_lands_on_scope_owner(self):
        """Majority assignment: every factor's shard owns at least
        one of its scope variables (otherwise every incidence would
        be cut — strictly worse than any scope shard)."""
        scopes, n = _grid_scopes(10)
        part = partition_factor_graph(scopes, n, 8)
        for sc, fs in zip(scopes, part.factor_shard):
            owner_hit = (part.var_shard[sc] == fs[:, None]).any(axis=1)
            assert owner_hit.all()

    def test_single_shard_degenerate(self):
        scopes, n = _grid_scopes(5)
        part = partition_factor_graph(scopes, n, 1)
        assert (part.var_shard == 0).all()
        assert part.stats["edge_cut_fraction"] == 0.0

    def test_adjacency_clique_for_high_arity(self):
        """Arity-3 scopes contribute their clique: all three pairs."""
        scopes = [np.asarray([[0, 1, 2]], np.int64)]
        nbrs, starts, ends = build_adjacency(scopes, 4)
        deg = ends - starts
        assert list(deg) == [2, 2, 2, 0]

    def test_cut_statistics_shape(self):
        scopes, n = _grid_scopes(6)
        part = partition_factor_graph(scopes, n, 4)
        s = part.stats
        assert s["cut_incidences"] <= s["total_incidences"]
        assert len(s["halo_vars_per_shard"]) == 4
        assert s["boundary_vars"] >= max(s["halo_vars_per_shard"])


class TestPartitionCache:
    def test_structure_keyed_hit(self):
        dcop = _grid_dcop(6)
        graph, _ = compile_dcop(dcop, noise_level=0.01)
        partition_cache.clear()
        a = partition_compiled(graph, 4)
        before = partition_cache.stats()
        b = partition_compiled(graph, 4)
        after = partition_cache.stats()
        assert after["hits"] == before["hits"] + 1
        assert after["builds"] == before["builds"]
        assert np.array_equal(a.var_shard, b.var_shard)

    def test_shard_count_in_key(self):
        dcop = _grid_dcop(6)
        graph, _ = compile_dcop(dcop, noise_level=0.01)
        partition_cache.clear()
        partition_compiled(graph, 2)
        partition_compiled(graph, 4)
        assert partition_cache.stats()["builds"] == 2

    def test_env_optout(self, monkeypatch):
        monkeypatch.setenv("PYDCOP_COMPILE_CACHE", "0")
        dcop = _grid_dcop(5)
        graph, _ = compile_dcop(dcop, noise_level=0.01,
                                use_cache=False)
        partition_cache.clear()
        partition_compiled(graph, 2)
        partition_compiled(graph, 2)
        stats = partition_cache.stats()
        assert stats["hits"] == 0
        assert stats["builds"] == 2


# --------------------------- partitioned engine --------------------- #


@needs_mesh
class TestPartitionedEngine:
    def test_comm_accounting_is_cut_times_d(self):
        from pydcop_tpu.algorithms.maxsum import build_engine

        dcop = _grid_dcop(10)
        engine = build_engine(dcop, {"noise": 0.01}, shards=8)
        m = engine.extra_metrics
        d = 3
        assert (m["halo_exchange_elems_per_superstep"]
                == m["boundary_vars"] * d)
        assert (m["replicated_allreduce_elems_per_superstep"]
                == (len(dcop.variables) + 1) * d)
        assert (m["halo_exchange_elems_per_superstep"]
                < m["replicated_allreduce_elems_per_superstep"])
        assert (m["halo_exchange_bytes_per_superstep"]
                == 4 * m["halo_exchange_elems_per_superstep"])

    def test_mixed_arity_parity(self):
        """Unary + binary + ternary factors through the partitioned
        kernels: local reindexing and the halo exchange must handle
        every bucket arity, not just the binary fast case."""
        from pydcop_tpu.algorithms.maxsum import build_engine

        dom = Domain("d", "", [0, 1, 2])
        dcop = DCOP("mixed", objective="min")
        vs = [Variable(f"v{i}", dom) for i in range(12)]
        for v in vs:
            dcop.add_variable(v)
        for i in range(12):
            dcop.add_constraint(constraint_from_str(
                f"u{i}", f"(v{i} - 1)**2", [vs[i]]))
            dcop.add_constraint(constraint_from_str(
                f"b{i}", f"abs(v{i} - v{(i + 1) % 12})",
                [vs[i], vs[(i + 1) % 12]]))
        for i in range(0, 12, 3):
            scope = [vs[i], vs[(i + 1) % 12], vs[(i + 2) % 12]]
            dcop.add_constraint(constraint_from_str(
                f"t{i}", f"v{i} * v{(i + 1) % 12} * v{(i + 2) % 12}",
                scope))
        params = {"noise": 0.01}
        r1 = build_engine(dcop, params).run(
            max_cycles=40, stop_on_convergence=False)
        r8 = build_engine(dcop, params, shards=8).run(
            max_cycles=40, stop_on_convergence=False)
        assert r8.assignment == r1.assignment

    def test_constraint_free_graph(self):
        """Zero factors → zero boundary buffer ([0, D] halo): the
        partitioned engine degenerates to per-variable argmin without
        crashing on empty collectives."""
        from pydcop_tpu.algorithms.maxsum import build_engine

        dom = Domain("d", "", [0, 1, 2])
        dcop = DCOP("free", objective="min")
        for i in range(8):
            dcop.add_variable(Variable(f"v{i}", dom))
        params = {"noise": 0.01}
        r1 = build_engine(dcop, params).run(max_cycles=5)
        r8 = build_engine(dcop, params, shards=8).run(max_cycles=5)
        assert r8.assignment == r1.assignment
        assert r8.metrics["boundary_vars"] == 0

    def test_guard_cost_matches_host(self):
        """ShardOps.assignment_constraint_cost (the recovery guard's
        verdict input) equals the host-evaluated constraint cost of
        the same global assignment."""
        from pydcop_tpu.algorithms.maxsum import build_engine

        dcop = _grid_dcop(8, seed=2)
        engine = build_engine(dcop, {"noise": 0.01}, shards=8)
        res = engine.run(max_cycles=30, stop_on_convergence=False)
        values = np.asarray([
            res.assignment[f"v{i}"] for i in range(len(dcop.variables))
        ], np.int32)
        device_cost = float(engine._ops.assignment_constraint_cost(
            engine.graph, values))
        host_cost, _ = dcop.solution_cost(res.assignment)
        assert device_cost == pytest.approx(host_cost)

    def test_maxsum_family_delegation(self):
        """amaxsum and maxsum_dynamic share maxsum's device engine,
        so shards= flows through their delegation (SUPPORTS_SHARDS)
        and produces the same partitioned result."""
        from pydcop_tpu.api import solve

        dcop = _grid_dcop(6)
        base = solve(dcop, "maxsum", max_cycles=30, shards=8)
        for algo in ("amaxsum", "maxsum_dynamic"):
            res = solve(dcop, algo, max_cycles=30, shards=8)
            assert res.assignment == base.assignment, algo
            assert res.cost == base.cost

    def test_decimation_rejected(self):
        from pydcop_tpu.algorithms.maxsum import build_engine

        dcop = _grid_dcop(6)
        with pytest.raises(ValueError, match="decimation"):
            build_engine(dcop, {"decimation": 10}, shards=8)

    def test_lane_layout_rejected(self):
        from pydcop_tpu.algorithms.maxsum import build_engine

        dcop = _grid_dcop(6)
        with pytest.raises(ValueError, match="lane"):
            build_engine(dcop, {"layout": "lane"}, shards=8)

    def test_non_scatter_aggregation_rejected(self):
        from pydcop_tpu.algorithms.maxsum import build_engine

        dcop = _grid_dcop(6)
        with pytest.raises(ValueError, match="scatter"):
            build_engine(dcop, {"aggregation": "ell"}, shards=8)

    def test_too_many_shards_message(self):
        from pydcop_tpu.algorithms.maxsum import build_engine

        dcop = _grid_dcop(6)
        with pytest.raises(ValueError,
                           match="xla_force_host_platform"):
            build_engine(dcop, {}, shards=64)


# ------------------------- per-shard trace lanes -------------------- #


@needs_mesh
class TestShardTraceLanes:
    def _sharded_trace(self, tmp_path, name):
        from pydcop_tpu.api import solve

        path = str(tmp_path / name)
        solve(_grid_dcop(8), "maxsum", max_cycles=30, shards=8,
              trace=path)
        return path

    def test_engine_spans_tagged_and_instants_emitted(self, tmp_path):
        from pydcop_tpu.observability.trace import load_trace_file

        events = load_trace_file(
            self._sharded_trace(tmp_path, "a.json"))
        segs = [e for e in events if e.get("name") == "engine_segment"]
        assert segs and all(
            e["args"].get("shards") == 8 for e in segs)
        shard_ids = {e["args"]["shard"] for e in events
                     if e.get("name") == "shard_segment"}
        assert shard_ids == set(range(8))

    def test_merge_separates_shard_lanes(self, tmp_path):
        """The satellite's lane-separation assertion: after ``pydcop
        trace merge``, every shard id occupies its OWN lane (distinct
        tid, labeled "[shard N]"), disjoint from the host thread
        lane."""
        from pydcop_tpu.observability.trace import merge_traces

        a = self._sharded_trace(tmp_path, "a.json")
        b = self._sharded_trace(tmp_path, "b.json")
        out = str(tmp_path / "merged.json")
        info = merge_traces([a, b], out)
        assert info["aligned"]
        doc = json.load(open(out))
        events = doc["traceEvents"]
        lane_labels = {
            e["tid"]: e["args"]["name"] for e in events
            if e.get("ph") == "M" and e.get("name") == "thread_name"
        }
        tids_per_file_shard = {}
        for e in events:
            if e.get("name") == "shard_segment":
                key = e["args"]["shard"]
                tids_per_file_shard.setdefault(key, set()).add(
                    e["tid"])
        # 8 shards x 2 files -> 16 distinct shard lanes, each
        # labeled with its shard id.
        all_shard_tids = set().union(*tids_per_file_shard.values())
        assert len(all_shard_tids) == 16
        for shard, tids in tids_per_file_shard.items():
            assert len(tids) == 2  # one lane per input file
            for tid in tids:
                assert f"[shard {shard}]" in lane_labels[tid]
        # Host-thread spans stay off the shard lanes.
        span_tids = {e["tid"] for e in events
                     if e.get("name") == "engine_segment"}
        assert span_tids.isdisjoint(all_shard_tids)


# ------------------------- bench sentinel series -------------------- #


class TestShardedSentinel:
    def _write_history(self, root, sharded_values):
        for i, v in enumerate(sharded_values, start=1):
            doc = {
                "n": i,
                "parsed": {
                    "metric":
                        "maxsum_cycles_per_sec_10kvar_graphcoloring",
                    "value": 800.0 + i,
                    "backend": "cpu",
                    "maxsum_cycles_per_sec_sharded": v,
                    "sharded_backend": "cpu",
                },
            }
            with open(os.path.join(root, f"BENCH_r{i:02d}.json"),
                      "w") as f:
                json.dump(doc, f)

    def test_sharded_series_ok(self, tmp_path):
        from bench_sentinel import run_check

        self._write_history(str(tmp_path), [700, 710, 695, 705, 702])
        report = run_check(str(tmp_path))
        assert not report["failed"]
        assert "sharded:cpu" in report["series"]
        assert report["series"]["sharded:cpu"]["verdict"] == "ok"
        assert any(line.startswith("sharded[cpu]")
                   for line in report["lines"])

    def test_sharded_regression_flagged(self, tmp_path):
        from bench_sentinel import run_check

        self._write_history(str(tmp_path), [700, 710, 695, 705, 420])
        report = run_check(str(tmp_path))
        assert report["failed"]
        assert report["series"]["sharded:cpu"]["verdict"] == "regressed"

    def test_missing_sharded_values_skipped(self, tmp_path):
        """Pre-PR-7 history rows carry no sharded key: the series
        simply starts later, never crashes the sentinel."""
        from bench_sentinel import run_check

        self._write_history(str(tmp_path), [None, None, 700, 705, 702])
        report = run_check(str(tmp_path))
        assert report["series"]["sharded:cpu"]["points"] == 3

"""Shared fixture-path helpers for the test suite.

The suite is self-contained: every battery runs against the original
instances committed under ``tests/instances/``.  When the reference
checkout is mounted at ``/root/reference`` an additional parity tier
re-runs the loader/golden batteries against the reference's own
fixture files verbatim; those tests skip cleanly anywhere the
reference isn't available.
"""

import glob
import os

import pytest

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
LOCAL_INSTANCES = os.path.join(TESTS_DIR, "instances")
# Override point so self-containment is testable without unmounting
# the checkout: PYDCOP_TPU_REF_INSTANCES=/nonexistent pytest tests/
REF_INSTANCES = os.environ.get(
    "PYDCOP_TPU_REF_INSTANCES", "/root/reference/tests/instances")
HAVE_REFERENCE = os.path.isdir(REF_INSTANCES)

requires_reference = pytest.mark.skipif(
    not HAVE_REFERENCE,
    reason="reference checkout not mounted at /root/reference",
)


def local(name):
    """Absolute path of a committed local instance file."""
    return os.path.join(LOCAL_INSTANCES, name)


def local_instances():
    """All committed local DCOP instance files (yaml + yml)."""
    return sorted(
        p for p in glob.glob(os.path.join(LOCAL_INSTANCES, "*.y*ml"))
        if not os.path.basename(p).startswith("scenario")
    )


def ref_instances():
    """Reference fixture files, [] when the checkout isn't mounted."""
    if not HAVE_REFERENCE:
        return []
    return sorted(glob.glob(os.path.join(REF_INSTANCES, "*.y*ml")))

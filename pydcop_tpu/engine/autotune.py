"""Aggregation autotuner: measure, don't guess.

The variable-side aggregation is the op that dominates the superstep
past the ~100k-var scale cliff (BENCH_TPU.md), and the best strategy
is backend- and shape-dependent: scatter wins everywhere on CPU,
while on TPU the scatter-add serializes row updates and the dense
ell gather is the candidate (docs/performance.md, round-5 on-chip
A/B).  A manual ``aggregation=`` flag nobody tunes leaves that
performance on the table; ``aggregation='auto'`` replaces it with a
per-graph measurement: micro-time the candidate strategies on the
*actual* compiled graph (same bucket shapes, same edge distribution,
random message payloads), pick the winner, and record the decision
in ``DeviceRunResult.metrics``.

Constraints the measurement respects (never violated, never silently
worked around):

- **mesh**: sharded graphs always use scatter (shard_graph drops the
  agg arrays) — callers resolve that before ever reaching here
  (engine/compile.validated_aggregation), and :func:`autotune_aggregation`
  re-checks ``pad_to``;
- **hub guard**: the ell builder refuses degree-skewed graphs whose
  padded lists would explode ([V+1, K] with K = max degree); the
  autotuner catches that refusal and drops ell from the candidate
  set instead of OOMing;
- **numerics**: "boundary" is timed for the record but NEVER
  selected — its f32 prefix sum cancels catastrophically at exactly
  the scale it targets (measured, docs/performance.md), which is why
  the maxsum param validation does not offer it either.

Decisions persist in a JSON cache keyed by (backend, graph shape):
re-serving a same-shaped problem skips the micro-benchmark entirely.
Default location ``~/.cache/pydcop_tpu/agg_autotune.json``
(``PYDCOP_AGG_AUTOTUNE_CACHE`` overrides; an unwritable path degrades
to measuring every time, never to failing the solve).
"""

import json
import logging
import os
import tempfile
from typing import Any, Dict, Optional

import numpy as np

from pydcop_tpu.engine.compile import (
    AGGREGATIONS,
    CompiledFactorGraph,
    build_aggregation_arrays,
)

logger = logging.getLogger("pydcop.engine.autotune")

# Strategies a solve may actually run with.  "boundary" is excluded
# on numerics (see module docstring), matching the algo-param policy.
SELECTABLE = ("scatter", "sorted", "ell")

_CACHE_VERSION = 1


def cache_path() -> str:
    env = os.environ.get("PYDCOP_AGG_AUTOTUNE_CACHE")
    if env:
        return env
    return os.path.join(
        os.path.expanduser("~"), ".cache", "pydcop_tpu",
        "agg_autotune.json",
    )


def shape_key(backend: str, n_vars: int, dmax: int,
              bucket_shapes, max_degree: int) -> str:
    """Stable string key for "same-shaped problem": backend + var/
    domain counts + per-bucket (arity, rows) + the max variable
    degree.  Cost values are deliberately absent — the aggregation op
    never reads them.  The degree term matters: the ell hub guard
    trips on max degree, so two graphs with identical bucket shapes
    but different degree skew must NOT share a cached 'ell' decision
    (a replay onto the hub-skewed twin would refuse to build).
    ``bucket_shapes`` is an iterable of (arity, rows), arity-sorted.
    """
    buckets = ";".join(f"{a}x{r}" for a, r in bucket_shapes)
    return (
        f"v{_CACHE_VERSION}|{backend}|V{n_vars}|D{dmax}"
        f"|{buckets}|K{max_degree}"
    )


def graph_max_degree(graph: CompiledFactorGraph) -> int:
    """Max real-variable degree over the flattened edge slots (the
    quantity the ell hub guard trips on; sentinel edges excluded)."""
    counts = np.zeros(graph.n_vars + 1, dtype=np.int64)
    for b in graph.buckets:
        counts += np.bincount(
            b.var_ids.reshape(-1), minlength=graph.n_vars + 1)
    return int(counts[:-1].max()) if graph.n_vars else 0


def graph_shape_key(graph: CompiledFactorGraph,
                    backend: Optional[str] = None) -> str:
    if backend is None:
        import jax

        backend = jax.default_backend()
    return shape_key(
        backend, graph.n_vars, graph.dmax,
        [(b.var_ids.shape[1], b.var_ids.shape[0])
         for b in graph.buckets],
        graph_max_degree(graph),
    )


def cached_choice(key: str,
                  cache_file: Optional[str] = None) -> Optional[str]:
    """Replay a persisted decision for ``key`` (None on miss/invalid)
    — lets callers resolve the strategy BEFORE compiling, so the
    winner's layout arrays come out of the compile-time structure
    cache instead of being rebuilt per solve."""
    cached = _load_cache(cache_file or cache_path()).get(key)
    if isinstance(cached, dict) \
            and cached.get("aggregation") in SELECTABLE:
        return cached["aggregation"]
    return None


def _load_cache(path: str) -> Dict[str, Any]:
    try:
        with open(path) as f:
            data = json.load(f)
        if isinstance(data, dict):
            return data
    except (OSError, ValueError):
        pass
    return {}


def _store_cache(path: str, data: Dict[str, Any]) -> None:
    """Atomic merge-and-write; failure logs and moves on (the cache
    is an optimization, not a dependency)."""
    try:
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        merged = _load_cache(path)
        merged.update(data)
        fd, tmp = tempfile.mkstemp(
            dir=directory, prefix=".autotune_", suffix=".json")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(merged, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except OSError as e:
        logger.warning("autotune cache not persisted to %s: %s",
                       path, e)


def apply_aggregation(graph: CompiledFactorGraph,
                      aggregation: str) -> CompiledFactorGraph:
    """Rebuild a compiled graph's agg_* arrays for ``aggregation``
    (structure-only: costs and var_ids are shared, not copied)."""
    perm, sorted_seg, starts, ends, ell = build_aggregation_arrays(
        graph.buckets, graph.n_vars + 1, aggregation
    )
    return graph._replace(
        agg_perm=perm, agg_sorted_seg=sorted_seg,
        agg_starts=starts, agg_ends=ends, agg_ell=ell,
    )


def _time_strategy(graph: CompiledFactorGraph, f2v, reps: int,
                   ) -> float:
    """Median seconds for one aggregation pass, warmed (compile
    excluded), honest completion via engine.timing.sync."""
    import jax

    from pydcop_tpu.engine.timing import sync, timed_call
    from pydcop_tpu.ops.maxsum import aggregate_beliefs

    fn = jax.jit(lambda g, m: aggregate_beliefs(g, m)[1])
    placed = jax.device_put(graph)
    sync(fn(placed, f2v))  # compile + warm
    times = [timed_call(fn, placed, f2v)[1] for _ in range(reps)]
    return float(np.median(times))


def autotune_aggregation(graph: CompiledFactorGraph, *,
                         pad_to: int = 1,
                         reps: int = 3,
                         use_cache: bool = True,
                         cache_file: Optional[str] = None,
                         ) -> Dict[str, Any]:
    """Pick the aggregation strategy for ``graph`` by measurement.

    Returns ``{"aggregation", "aggregation_source",
    "aggregation_timings_ms", "aggregation_key"}`` — the dict engines
    merge into ``DeviceRunResult.metrics``.  ``aggregation_source``
    is one of:

    - ``"mesh"``: sharded run, scatter is the only valid strategy
      (nothing measured);
    - ``"empty"``: no factor edges, nothing to aggregate;
    - ``"cache"``: decision replayed from the JSON shape cache;
    - ``"measured"``: micro-benchmarked on this process's backend.

    Timings are reported for all four named strategies where
    measurable (``None`` where not: hub-guard refusals, mesh runs);
    selection only ever happens among :data:`SELECTABLE`.
    """
    import jax

    backend = jax.default_backend()
    key = graph_shape_key(graph, backend)
    timings: Dict[str, Optional[float]] = {
        s: None for s in AGGREGATIONS}
    if pad_to > 1:
        return {
            "aggregation": "scatter",
            "aggregation_source": "mesh",
            "aggregation_timings_ms": timings,
            "aggregation_key": key,
        }
    n_edges = sum(
        int(np.prod(b.var_ids.shape)) for b in graph.buckets)
    if n_edges == 0:
        return {
            "aggregation": "scatter",
            "aggregation_source": "empty",
            "aggregation_timings_ms": timings,
            "aggregation_key": key,
        }

    path = cache_file or cache_path()
    if use_cache:
        cached = _load_cache(path).get(key)
        if (isinstance(cached, dict)
                and cached.get("aggregation") in SELECTABLE):
            return {
                "aggregation": cached["aggregation"],
                "aggregation_source": "cache",
                "aggregation_timings_ms": cached.get(
                    "aggregation_timings_ms", timings),
                "aggregation_key": key,
            }

    # Random message payloads: the aggregation's cost is layout- and
    # index-driven, value-independent — any dense payload measures it.
    # Placed on device ONCE: host-resident payloads would add the
    # same multi-MB host→device transfer to every rep of every
    # strategy, drowning the kernel-time differences being measured.
    rng = np.random.default_rng(0)
    d = graph.dmax
    f2v = jax.device_put(tuple(
        rng.standard_normal(
            b.var_ids.shape + (d,)).astype(np.float32)
        for b in graph.buckets
    ))
    notes: Dict[str, str] = {}
    for strategy in AGGREGATIONS:
        try:
            variant = apply_aggregation(graph, strategy)
        except ValueError as e:
            # The hub guard refusing ell (or any builder refusal):
            # record why, drop the candidate.
            notes[strategy] = str(e).split(":")[0]
            continue
        try:
            timings[strategy] = _time_strategy(variant, f2v, reps)
        except Exception as e:  # pragma: no cover - backend-specific
            notes[strategy] = f"{type(e).__name__}"
            logger.warning("autotune: %s failed to run: %s",
                           strategy, e)

    candidates = {
        s: t for s, t in timings.items()
        if s in SELECTABLE and t is not None
    }
    # Deterministic tie-break: strategy order in SELECTABLE (scatter
    # first — the parity default) wins exact ties.
    choice = min(
        candidates,
        key=lambda s: (candidates[s], SELECTABLE.index(s)),
    ) if candidates else "scatter"
    timings_ms = {
        s: (None if t is None else round(t * 1e3, 4))
        for s, t in timings.items()
    }
    result = {
        "aggregation": choice,
        "aggregation_source": "measured",
        "aggregation_timings_ms": timings_ms,
        "aggregation_key": key,
    }
    if notes:
        result["aggregation_notes"] = notes
    if use_cache:
        _store_cache(path, {key: {
            "aggregation": choice,
            "aggregation_timings_ms": timings_ms,
            "backend": backend,
        }})
    return result

"""Algorithm plugin machinery: descriptors, parameter validation,
module discovery.

Reference parity: pydcop/algorithms/__init__.py (ALGO_STOP/CONTINUE :93,
AlgoParameterDef :99, AlgorithmDef :141, ComputationDef :336,
check_param_value :383, prepare_algo_params :446,
list_available_algorithms :508, load_algorithm_module :528).

The plugin contract (reference docs/implementation/algorithms.rst:18-241):
an algorithm module declares ``GRAPH_TYPE``, optional ``algo_params``,
``build_computation`` (agent mode), ``computation_memory``,
``communication_load``; missing pieces get defaults injected at load.
TPU addition to the contract: a module may declare
``solve_on_device(dcop, algo_def, max_cycles, mesh, ...)`` — the batched
engine path used when the backend is ``device``.  Drop a module in this
package and it becomes a CLI ``--algo`` value.
"""

import importlib
import pkgutil
from typing import Any, Dict, List, NamedTuple, Optional

from pydcop_tpu.computations_graph.objects import ComputationNode
from pydcop_tpu.utils.simple_repr import SimpleRepr, from_repr, simple_repr

# Stop-condition semantics for agent-mode computations.
ALGO_STOP = "stop"
ALGO_CONTINUE = "continue"
ALGO_NO_STOP_CONDITION = "no_stop_condition"


class AlgoParameterDef(NamedTuple):
    """Declaration of one algorithm parameter."""

    name: str
    type: str                       # 'str' | 'int' | 'float' | 'bool'
    values: Optional[List] = None   # allowed values, or None
    default_value: Any = None


class AlgoParameterException(Exception):
    pass


def check_param_value(value: Any, param_def: AlgoParameterDef) -> Any:
    """Coerce and validate a parameter value against its definition."""
    if value is None:
        return param_def.default_value
    try:
        if param_def.type == "int":
            value = int(value)
        elif param_def.type == "float":
            value = float(value)
        elif param_def.type == "bool":
            if isinstance(value, str):
                value = value.lower() in ("true", "1", "yes")
            else:
                value = bool(value)
        elif param_def.type == "str":
            value = str(value)
    except (ValueError, TypeError):
        raise AlgoParameterException(
            f"Invalid value {value!r} for parameter {param_def.name} "
            f"of type {param_def.type}"
        )
    if param_def.values is not None and value not in param_def.values:
        raise AlgoParameterException(
            f"Value {value!r} for parameter {param_def.name} not in "
            f"allowed values {param_def.values}"
        )
    return value


def prepare_algo_params(params: Dict[str, Any],
                        params_defs: List[AlgoParameterDef]
                        ) -> Dict[str, Any]:
    """Full parameter dict: given values validated, defaults filled in.
    Unknown parameter names raise."""
    defs = {p.name: p for p in params_defs}
    unknown = set(params) - set(defs)
    if unknown:
        raise AlgoParameterException(
            f"Unknown algorithm parameter(s): {sorted(unknown)}; "
            f"supported: {sorted(defs)}"
        )
    out = {}
    for name, pdef in defs.items():
        out[name] = check_param_value(params.get(name), pdef)
    return out


class AlgorithmDef(SimpleRepr):
    """An algorithm selection: name + validated params + objective mode."""

    def __init__(self, algo: str, params: Dict[str, Any],
                 mode: str = "min"):
        self._algo = algo
        self._params = dict(params)
        self._mode = mode

    @classmethod
    def build_with_default_param(cls, algo: str,
                                 params: Optional[Dict] = None,
                                 mode: str = "min",
                                 parameters_definitions:
                                 Optional[List[AlgoParameterDef]] = None,
                                 ) -> "AlgorithmDef":
        if parameters_definitions is None:
            module = load_algorithm_module(algo)
            parameters_definitions = module.algo_params
        full = prepare_algo_params(params or {}, parameters_definitions)
        return cls(algo, full, mode)

    @property
    def algo(self) -> str:
        return self._algo

    @property
    def params(self) -> Dict[str, Any]:
        return dict(self._params)

    @property
    def mode(self) -> str:
        return self._mode

    def param_value(self, name: str) -> Any:
        return self._params[name]

    def __eq__(self, other):
        return (
            isinstance(other, AlgorithmDef)
            and self._algo == other._algo
            and self._params == other._params
            and self._mode == other._mode
        )

    def __repr__(self):
        return f"AlgorithmDef({self._algo}, {self._params}, {self._mode})"


class ComputationDef(SimpleRepr):
    """Everything needed to instantiate one computation: its node in the
    computation graph + the algorithm to run on it."""

    def __init__(self, node: ComputationNode, algo: AlgorithmDef):
        self._node = node
        self._algo = algo

    @property
    def node(self) -> ComputationNode:
        return self._node

    @property
    def algo(self) -> AlgorithmDef:
        return self._algo

    @property
    def name(self) -> str:
        return self._node.name

    def __repr__(self):
        return f"ComputationDef({self.name}, {self._algo.algo})"


def list_available_algorithms() -> List[str]:
    """All algorithm modules in this package (plugin discovery)."""
    import pydcop_tpu.algorithms as pkg

    return sorted(
        name
        for _, name, ispkg in pkgutil.iter_modules(pkg.__path__)
        if not ispkg and not name.startswith("_")
    )


def _default_computation_memory(node: ComputationNode) -> float:
    return 0.0


def _default_communication_load(src: ComputationNode,
                                target: str) -> float:
    return 1.0


def load_algorithm_module(name: str):
    """Import an algorithm module, injecting contract defaults for any
    missing optional pieces (reference behavior, algorithms/__init__.py
    :528-566)."""
    module = importlib.import_module(f"pydcop_tpu.algorithms.{name}")
    if not hasattr(module, "algo_params"):
        module.algo_params = []
    if not hasattr(module, "communication_load"):
        module.communication_load = _default_communication_load
    if not hasattr(module, "computation_memory"):
        module.computation_memory = _default_computation_memory
    if not hasattr(module, "GRAPH_TYPE"):
        raise AttributeError(
            f"Algorithm module {name} must declare GRAPH_TYPE"
        )
    return module


def find_computation_implementation(algo_name: str, comp_def):
    """Agent-mode factory: build the computation object for a node."""
    module = load_algorithm_module(algo_name)
    return module.build_computation(comp_def)

"""SECP-specific placement rules, shared by the gh_secp_* / oilp_secp_*
distribution methods.

The SECP (smart-lighting) placement conventions these encode
(reference: pydcop/distribution/gh_secp_cgdp.py:75-124,
gh_secp_fgdp.py:92-198, oilp_secp_fgdp.py:72-131):

1. **Actuator pinning.** A variable whose hosting cost on some agent is
   0 represents that agent's actuator (light) and MUST be hosted there.
2. **Cost-factor co-location** (factor graph only). The actuator's
   energy cost factor is named ``c_<actuator>`` and goes on the same
   agent.
3. **Physical-model pairing** (factor graph only). After pinning, every
   remaining variable is a physical-model variable ``m`` whose defining
   factor is named ``c_<m>``; both are placed *together*.
4. **Neighbor affinity** (greedy flavor). Each remaining computation
   goes to the agent that (a) has capacity left and (b) hosts the most
   computations sharing a dependency with it; ties break on the largest
   remaining capacity.  Every candidate must host >= 1 neighbor — model
   factors always depend on at least one already-pinned actuator, so a
   candidate always exists on well-formed SECPs.
"""

from collections import defaultdict
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from pydcop_tpu.distribution.objects import (
    ImpossibleDistributionException,
)


def split_fg_nodes(cg) -> Tuple[List[str], List[str]]:
    """(variable computation names, factor computation names) of a
    factor graph, in graph order."""
    from pydcop_tpu.computations_graph.factor_graph import (
        FactorComputationNode,
        VariableComputationNode,
    )

    variables, factors = [], []
    for node in cg.nodes:
        if isinstance(node, VariableComputationNode):
            variables.append(node.name)
        elif isinstance(node, FactorComputationNode):
            factors.append(node.name)
        else:
            raise ImpossibleDistributionException(
                f"{node.name} is neither a factor nor a variable "
                "computation"
            )
    return variables, factors


def _footprint(cg, computation_memory: Optional[Callable],
               comp: str) -> float:
    if computation_memory is None:
        return 0.0
    try:
        return float(computation_memory(cg.computation(comp)))
    except (NotImplementedError, TypeError):
        return 0.0


def pin_actuators(
    cg, agentsdef: Iterable, computation_memory: Optional[Callable],
    *, candidates: Optional[List[str]] = None,
    cost_factors: Optional[List[str]] = None,
) -> Tuple[Dict[str, List[str]], Dict[str, float], List[str],
           Optional[List[str]]]:
    """Place every actuator computation (hosting cost 0) on its agent,
    plus — when ``cost_factors`` is given — its ``c_<name>`` factor.

    Returns (mapping, remaining capacity per agent, unpinned candidate
    computations, unpinned cost factors or None).
    """
    agents = list(agentsdef)
    mapping: Dict[str, List[str]] = defaultdict(list)
    capa = {a.name: _capacity(a) for a in agents}
    remaining = list(
        candidates if candidates is not None
        else [n.name for n in cg.nodes]
    )
    factors = list(cost_factors) if cost_factors is not None else None

    # Pin EVERY zero-hosting-cost computation of each agent (the
    # reference's per-agent scan stops after the first hit because its
    # generator emits exactly one actuator per agent; pinning all is
    # the same on well-formed SECPs and consistent with oilp_cgdp's
    # force-zero-cost rule on multi-actuator agents).
    for agent in agents:
        for comp in list(remaining):
            if agent.hosting_cost(comp) == 0:
                mapping[agent.name].append(comp)
                remaining.remove(comp)
                capa[agent.name] -= _footprint(
                    cg, computation_memory, comp)
                if factors is not None:
                    paired = f"c_{comp}"
                    if paired in factors:
                        mapping[agent.name].append(paired)
                        factors.remove(paired)
                        capa[agent.name] -= _footprint(
                            cg, computation_memory, paired)
                if capa[agent.name] < 0:
                    raise ImpossibleDistributionException(
                        f"Not enough capacity on {agent.name} to host "
                        f"actuator {comp}"
                    )
    return mapping, capa, remaining, factors


def _capacity(agent) -> float:
    try:
        return float(agent.capacity)
    except (AttributeError, TypeError):
        return float("inf")


def affinity_candidates(
    capa: Dict[str, float], comp: str, footprint: float,
    mapping: Dict[str, List[str]], neighbors: Iterable[str],
) -> List[Tuple[int, float, str]]:
    """Agents with capacity hosting >=1 neighbor of ``comp``, best
    first: most hosted neighbors, then largest remaining capacity
    (reference gh_secp_cgdp.py:142-166 find_candidates)."""
    neighbor_set = set(neighbors)
    out = []
    for agent, cap in capa.items():
        hosted = len(neighbor_set.intersection(mapping.get(agent, ())))
        if hosted > 0 and cap >= footprint:
            out.append((hosted, cap, agent))
    if not out:
        raise ImpossibleDistributionException(
            f"No neighbor-hosting agent with capacity for {comp} "
            f"(footprint {footprint})"
        )
    out.sort(reverse=True)
    return out


def place_by_affinity(
    cg, computation_memory: Optional[Callable],
    mapping: Dict[str, List[str]], capa: Dict[str, float],
    groups: Iterable[Tuple[str, ...]],
) -> None:
    """Place each group of computations (together) on the best
    affinity candidate; the group's first member is the anchor whose
    neighbors drive the choice (e.g. the model *factor* for a
    (c_m, m) pair, reference gh_secp_fgdp.py:166-181)."""
    for group in groups:
        anchor = group[0]
        footprint = sum(
            _footprint(cg, computation_memory, c) for c in group
        )
        neighbors = cg.computation(anchor).neighbors
        best = affinity_candidates(
            capa, anchor, footprint, mapping, neighbors)
        selected = best[0][2]
        for c in group:
            mapping[selected].append(c)
        capa[selected] -= footprint

"""Lane-major MaxSum (ops/maxsum_lane.py) parity vs the edge-major
kernels — the CPU bit-parity contract behind the ``layout="lane"``
algo param.

Parity tiers (module docstring of maxsum_lane explains why they
differ):

- factor update and variable update are elementwise/tiny-D ops in
  identical order across layouts → BIT-equal given equal inputs;
- variable aggregation sums each variable's incoming edges in a
  different order (edge-major flattens (factor, position), lane-major
  (position, factor)) → bit-equal whenever each variable has at most
  one incoming edge, float-tolerance otherwise;
- whole trajectories → identical selected assignments and cycle
  counts on well-separated instances (seeded), messages to float
  tolerance.
"""

import os

import numpy as np
import pytest

import jax

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation, constraint_from_str
from pydcop_tpu.engine.compile import compile_dcop, compile_factor_graph
from pydcop_tpu.engine.runner import MaxSumEngine
from pydcop_tpu.ops import maxsum as edge_ops
from pydcop_tpu.ops import maxsum_lane as lane_ops


def _random_dcop(n_vars=12, n_edges=18, d=3, seed=0, ternary=False):
    rng = np.random.default_rng(seed)
    dom = Domain("d", "", list(range(d)))
    dcop = DCOP("rand", objective="min")
    variables = [Variable(f"v{i}", dom) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    seen = set()
    k = 0
    while k < n_edges:
        i, j = rng.choice(n_vars, size=2, replace=False)
        key = (min(i, j), max(i, j))
        if key in seen:
            continue
        seen.add(key)
        table = rng.integers(0, 10, size=(d, d)).astype(np.float64)
        dcop.add_constraint(NAryMatrixRelation(
            [variables[i], variables[j]], table, f"c{k}"))
        k += 1
    if ternary:
        i, j, l = rng.choice(n_vars, size=3, replace=False)
        table = rng.integers(0, 10, size=(d, d, d)).astype(np.float64)
        dcop.add_constraint(NAryMatrixRelation(
            [variables[i], variables[j], variables[l]], table, "t0"))
    return dcop


def _lane_to_edge_msgs(msgs):
    """[D, a, F] -> [F, a, D] for comparisons."""
    return tuple(np.transpose(np.asarray(m), (2, 1, 0)) for m in msgs)


def _edge_to_lane_msgs(msgs):
    return tuple(np.transpose(np.asarray(m), (2, 1, 0)) for m in msgs)


class TestRelayout:
    def test_to_lane_graph_shapes(self):
        graph, _ = compile_dcop(_random_dcop(ternary=True))
        lane = lane_ops.to_lane_graph(graph)
        assert lane.var_costs.shape == graph.var_costs.shape[::-1]
        assert lane.n_vars == graph.n_vars
        assert lane.dmax == graph.dmax
        for eb, lb in zip(graph.buckets, lane.buckets):
            assert lb.arity == eb.arity
            assert lb.n_factors == eb.n_factors
            assert lb.var_ids.shape == eb.var_ids.shape[::-1]
            np.testing.assert_array_equal(
                np.asarray(lb.var_ids), np.asarray(eb.var_ids).T)
            np.testing.assert_array_equal(
                np.moveaxis(np.asarray(lb.costs), -1, 0),
                np.asarray(eb.costs))

    def test_lane_requires_scatter(self):
        graph, meta = compile_dcop(_random_dcop(), aggregation="sorted")
        with pytest.raises(ValueError, match="scatter"):
            MaxSumEngine(graph, meta, layout="lane")

    def test_lane_is_single_device(self):
        graph, meta = compile_dcop(_random_dcop(), pad_to=8)
        with pytest.raises(ValueError, match="single-device"):
            MaxSumEngine(graph, meta, layout="lane", n_devices=8)

    def test_bad_layout_rejected(self):
        graph, meta = compile_dcop(_random_dcop())
        with pytest.raises(ValueError, match="layout"):
            MaxSumEngine(graph, meta, layout="columns")


class TestOpParity:
    """Single-op comparisons on equal inputs."""

    def _graphs(self, **kw):
        graph, _ = compile_dcop(_random_dcop(**kw), noise_level=0.01)
        return graph, lane_ops.to_lane_graph(graph)

    def _random_msgs(self, graph, seed=1):
        rng = np.random.default_rng(seed)
        d = graph.var_costs.shape[1]
        return tuple(
            rng.random(b.var_ids.shape + (d,)).astype(np.float32)
            for b in graph.buckets
        )

    def test_factor_update_bit_equal(self):
        graph, lane = self._graphs(ternary=True)
        v2f = self._random_msgs(graph)
        edge_out = edge_ops.factor_to_var(graph, v2f)
        lane_out = lane_ops.factor_to_var(lane, _edge_to_lane_msgs(v2f))
        for e, l in zip(edge_out, _lane_to_edge_msgs(lane_out)):
            np.testing.assert_array_equal(np.asarray(e), l)

    def test_var_update_bit_equal(self):
        graph, lane = self._graphs(ternary=True)
        f2v = self._random_msgs(graph, seed=2)
        beliefs, sums = edge_ops.aggregate_beliefs(graph, f2v)
        edge_out = edge_ops.var_to_factor(graph, f2v, beliefs, sums)
        lane_out = lane_ops.var_to_factor(
            lane, _edge_to_lane_msgs(f2v),
            np.asarray(beliefs).T, np.asarray(sums).T)
        for e, l in zip(edge_out, _lane_to_edge_msgs(lane_out)):
            np.testing.assert_array_equal(np.asarray(e), l)

    def test_aggregation_bit_equal_single_edge_vars(self):
        """A matching: every variable has exactly one incoming edge, so
        the per-variable sum has one term and reassociation cannot
        differ — the layouts must agree bitwise."""
        d = Domain("d", "", [0, 1, 2])
        variables = [Variable(f"v{i}", d) for i in range(8)]
        cons = [
            constraint_from_str(
                f"c{i}", f"v{2*i} + 2 * v{2*i+1}",
                [variables[2 * i], variables[2 * i + 1]])
            for i in range(4)
        ]
        graph, _ = compile_factor_graph(variables, cons)
        lane = lane_ops.to_lane_graph(graph)
        f2v = self._random_msgs(graph, seed=3)
        eb, es = edge_ops.aggregate_beliefs(graph, f2v)
        lb, ls = lane_ops.aggregate_beliefs(
            lane, _edge_to_lane_msgs(f2v))
        np.testing.assert_array_equal(np.asarray(eb), np.asarray(lb).T)
        np.testing.assert_array_equal(np.asarray(es), np.asarray(ls).T)

    def test_aggregation_close_general(self):
        graph, lane = self._graphs(ternary=True)
        f2v = self._random_msgs(graph, seed=4)
        eb, _ = edge_ops.aggregate_beliefs(graph, f2v)
        lb, _ = lane_ops.aggregate_beliefs(
            lane, _edge_to_lane_msgs(f2v))
        np.testing.assert_allclose(
            np.asarray(eb), np.asarray(lb).T, rtol=1e-6, atol=1e-5)

    def test_select_values_match(self):
        graph, lane = self._graphs()
        rng = np.random.default_rng(5)
        beliefs = rng.random(graph.var_costs.shape).astype(np.float32)
        e = edge_ops.select_values(graph, beliefs)
        l = lane_ops.select_values(lane, beliefs.T)
        np.testing.assert_array_equal(np.asarray(e), np.asarray(l))

    def test_assignment_cost_bit_equal(self):
        graph, lane = self._graphs(ternary=True)
        rng = np.random.default_rng(6)
        values = rng.integers(0, 3, size=graph.n_vars).astype(np.int32)
        e = edge_ops.assignment_constraint_cost(graph, values)
        l = lane_ops.assignment_constraint_cost(lane, values)
        assert float(e) == float(l)


class TestRunParity:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    @pytest.mark.parametrize("stop", [True, False])
    def test_whole_run(self, seed, stop):
        dcop = _random_dcop(seed=seed, ternary=(seed == 2))
        graph, _ = compile_dcop(dcop, noise_level=0.01)
        lane = lane_ops.to_lane_graph(graph)
        es, ev = jax.jit(
            lambda g: edge_ops.run_maxsum(
                g, 60, stop_on_convergence=stop))(graph)
        ls, lv = jax.jit(
            lambda g: lane_ops.run_maxsum(
                g, 60, stop_on_convergence=stop))(lane)
        assert int(es.cycle) == int(ls.cycle)
        assert bool(es.stable) == bool(ls.stable)
        np.testing.assert_array_equal(
            np.asarray(ev), np.asarray(lv))
        for e, l in zip(es.f2v, _lane_to_edge_msgs(ls.f2v)):
            np.testing.assert_allclose(
                np.asarray(e), l, rtol=1e-5, atol=1e-4)
        for e, l in zip(es.v2f, _lane_to_edge_msgs(ls.v2f)):
            np.testing.assert_allclose(
                np.asarray(e), l, rtol=1e-5, atol=1e-4)

    def test_trace_parity(self):
        dcop = _random_dcop(seed=7)
        graph, meta = compile_dcop(dcop, noise_level=0.01)
        lane = lane_ops.to_lane_graph(graph)
        base = meta.var_base_costs
        _, ev, ec = jax.jit(lambda g: edge_ops.run_maxsum_trace(
            g, 25, var_base_costs=base))(graph)
        _, lv, lc = jax.jit(lambda g: lane_ops.run_maxsum_trace(
            g, 25, var_base_costs=base))(lane)
        np.testing.assert_array_equal(np.asarray(ev), np.asarray(lv))
        np.testing.assert_allclose(
            np.asarray(ec), np.asarray(lc), rtol=1e-6, atol=1e-4)


class TestEngineLayout:
    def test_engine_lane_matches_edge(self):
        dcop = _random_dcop(seed=9)
        graph, meta = compile_dcop(dcop, noise_level=0.01)
        edge_res = MaxSumEngine(graph, meta).run(max_cycles=50)
        lane_res = MaxSumEngine(graph, meta, layout="lane").run(
            max_cycles=50)
        assert lane_res.assignment == edge_res.assignment
        assert lane_res.cycles == edge_res.cycles
        assert lane_res.converged == edge_res.converged

    def test_engine_lane_trace(self):
        dcop = _random_dcop(seed=10)
        graph, meta = compile_dcop(dcop, noise_level=0.01)
        edge_res = MaxSumEngine(graph, meta).run_trace(max_cycles=20)
        lane_res = MaxSumEngine(graph, meta, layout="lane").run_trace(
            max_cycles=20)
        np.testing.assert_allclose(
            lane_res.metrics["cost_trace"],
            edge_res.metrics["cost_trace"], rtol=1e-6, atol=1e-4)

    def test_engine_lane_rejects_decimation(self):
        graph, meta = compile_dcop(_random_dcop())
        eng = MaxSumEngine(graph, meta, layout="lane")
        with pytest.raises(ValueError, match="edge"):
            eng.run_decimated(max_cycles=10)

    def test_solve_with_layout_param(self):
        from pydcop_tpu.api import solve

        dcop = _random_dcop(seed=11)
        edge = solve(dcop, "maxsum", backend="device", max_cycles=40,
                     algo_params={"layout": "edge"})
        lane = solve(dcop, "maxsum", backend="device", max_cycles=40,
                     algo_params={"layout": "lane"})
        assert lane.assignment == edge.assignment


REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


class TestBenchScaleLayout:
    def test_bench_scale_lane_agrees(self):
        import sys

        sys.path.insert(0, REPO_ROOT)
        import bench as bench_mod
        from functools import partial

        _, edge_graph = bench_mod.bench_scale(
            n_vars=300, cycles=10, layout="edge")
        _, lane_graph = bench_mod.bench_scale(
            n_vars=300, cycles=10, layout="lane")
        _, ev = jax.jit(partial(
            edge_ops.run_maxsum, max_cycles=10,
            stop_on_convergence=False))(edge_graph)
        _, lv = jax.jit(partial(
            lane_ops.run_maxsum, max_cycles=10,
            stop_on_convergence=False))(lane_graph)
        agree = np.mean(np.asarray(ev) == np.asarray(lv))
        assert agree > 0.99

    def test_bench_scale_lane_rejects_sorted(self):
        import sys

        sys.path.insert(0, REPO_ROOT)
        import bench as bench_mod

        with pytest.raises(ValueError, match="scatter"):
            bench_mod.bench_scale(
                n_vars=100, cycles=2, aggregation="sorted",
                layout="lane")

"""Infrastructure unit tests.

The reference's testing trick (tests/unit/
test_infra_synchronous_computation.py:25): drive computations directly
with a mocked message sender — no agents, no threads.
"""

from unittest.mock import MagicMock

import pytest

from pydcop_tpu.algorithms import AlgorithmDef, ComputationDef
from pydcop_tpu.computations_graph import constraints_hypergraph as chg
from pydcop_tpu.computations_graph import factor_graph as fg
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.infrastructure.agent_algorithms import (
    DsaComputation,
    MaxSumFactorComputation,
    MaxSumVariableComputation,
    MgmComputation,
    MaxSumMessage,
    approx_match,
    costs_for_factor,
    factor_costs_for_var,
)
from pydcop_tpu.infrastructure.computations import (
    ComputationException,
    Message,
    MessagePassingComputation,
    message_type,
    register,
)
from pydcop_tpu.utils.simple_repr import from_repr, simple_repr

d3 = Domain("d", "", [0, 1, 2])


class TestMessageType:
    def test_factory(self):
        VMsg = message_type("vmsg", ["value", "cost"])
        m = VMsg(value=1, cost=2.0)
        assert m.value == 1 and m.cost == 2.0
        assert m.type == "vmsg"
        assert m.size == 2

    def test_positional(self):
        VMsg = message_type("vmsg", ["value"])
        assert VMsg(7).value == 7

    def test_missing_field_raises(self):
        VMsg = message_type("vmsg", ["value"])
        with pytest.raises(ValueError):
            VMsg()

    def test_simple_repr_roundtrip(self):
        m = MaxSumMessage({0: 1.5, 1: 2.5})
        m2 = from_repr(simple_repr(m))
        assert m2 == m


class TestMessagePassingComputation:
    def _comp(self):
        class C(MessagePassingComputation):
            seen = []

            @register("test_msg")
            def on_test(self, sender, msg, t):
                self.seen.append((sender, msg.content))

        c = C("c1")
        c._msg_sender = MagicMock()
        return c

    def test_dispatch(self):
        c = self._comp()
        c.start()
        c.on_message("other", Message("test_msg", 42), 0)
        assert c.seen == [("other", 42)]

    def test_unknown_type_raises(self):
        c = self._comp()
        c.start()
        with pytest.raises(ComputationException):
            c.on_message("other", Message("nope", 1), 0)

    def test_pause_buffers_messages(self):
        c = self._comp()
        c.seen = []
        c.start()
        c.pause(True)
        c.on_message("o", Message("test_msg", 1), 0)
        assert c.seen == []
        c.pause(False)
        assert c.seen == [("o", 1)]

    def test_post_msg_uses_sender(self):
        c = self._comp()
        c.start()
        c.post_msg("target", Message("test_msg", 5))
        c._msg_sender.assert_called_once()
        args = c._msg_sender.call_args[0]
        assert args[0] == "c1" and args[1] == "target"


def _maxsum_comp_defs():
    v1 = Variable("v1", d3)
    v2 = Variable("v2", d3)
    c1 = constraint_from_str("c1", "abs(v1 - v2)", [v1, v2])
    graph = fg.build_computation_graph(variables=[v1, v2],
                                       constraints=[c1])
    algo = AlgorithmDef.build_with_default_param("maxsum", {}, "min")
    defs = {
        n.name: ComputationDef(n, algo) for n in graph.nodes
    }
    return defs


class TestMaxSumComputations:
    def test_factor_costs_for_var(self):
        v1, v2 = Variable("v1", d3), Variable("v2", d3)
        c = constraint_from_str("c", "v1 * 3 + v2", [v1, v2])
        costs = factor_costs_for_var(c, v1, {"v2": {0: 0, 1: 5, 2: 5}},
                                     "min")
        # For v1=d: min over v2 of (3d + v2 + recv[v2]) = 3d + 0
        assert costs == {0: 0, 1: 3, 2: 6}

    def test_costs_for_factor_normalized(self):
        v = Variable("v", d3)
        costs = costs_for_factor(
            v, "f1", ["f1", "f2"], {"f2": {0: 3, 1: 6, 2: 0}}
        )
        assert costs == {0: 0, 1: 3, 2: -3}
        assert abs(sum(costs.values())) < 1e-9

    def test_approx_match(self):
        assert approx_match({0: 1.0}, {0: 1.0}, 0.1)
        assert approx_match({0: 1.0}, {0: 1.01}, 0.1)
        assert not approx_match({0: 1.0}, {0: 2.0}, 0.1)
        assert not approx_match({0: 1.0}, None, 0.1)

    def test_computation_wiring(self):
        defs = _maxsum_comp_defs()
        vc = MaxSumVariableComputation(defs["v1"])
        fc = MaxSumFactorComputation(defs["c1"])
        assert vc.neighbors == ["c1"]
        assert set(fc.neighbors) == {"v1", "v2"}
        vc._msg_sender = MagicMock()
        vc.start()
        # Initial value selected from (noisy) own costs
        assert vc.current_value in d3
        # Sync mixin sent cycle-stamped messages to the factor
        sent = [c[0][2] for c in vc._msg_sender.call_args_list]
        assert all(m.type == "_cycle" for m in sent)

    def test_sync_cycle_advance(self):
        defs = _maxsum_comp_defs()
        fc = MaxSumFactorComputation(defs["c1"])
        fc._msg_sender = MagicMock()
        fc.start()
        assert fc.cycle_id == 0
        # Deliver one cycle-0 message from each neighbor variable:
        for v in ("v1", "v2"):
            fc.on_message(
                v, Message("_cycle", (0, MaxSumMessage({0: 0, 1: 0, 2: 0}))),
                0,
            )
        assert fc.cycle_id == 1

    def test_sync_duplicate_message_raises(self):
        defs = _maxsum_comp_defs()
        fc = MaxSumFactorComputation(defs["c1"])
        fc._msg_sender = MagicMock()
        fc.start()
        fc.on_message(
            "v1", Message("_cycle", (0, MaxSumMessage({0: 0}))), 0)
        with pytest.raises(ComputationException):
            fc.on_message(
                "v1", Message("_cycle", (0, MaxSumMessage({0: 1}))), 0)

    def test_sync_out_of_cycle_raises(self):
        defs = _maxsum_comp_defs()
        fc = MaxSumFactorComputation(defs["c1"])
        fc._msg_sender = MagicMock()
        fc.start()
        with pytest.raises(ComputationException):
            fc.on_message(
                "v1", Message("_cycle", (5, MaxSumMessage({0: 0}))), 0)


class TestDsaComputation:
    def _dsa(self, variant="B"):
        v1 = Variable("v1", d3)
        v2 = Variable("v2", d3)
        c1 = constraint_from_str("c1", "1 if v1 == v2 else 0", [v1, v2])
        graph = chg.build_computation_graph(
            variables=[v1, v2], constraints=[c1])
        algo = AlgorithmDef.build_with_default_param(
            "dsa", {"variant": variant, "probability": 1.0}, "min")
        node = next(n for n in graph.nodes if n.name == "v1")
        comp = DsaComputation(ComputationDef(node, algo))
        comp._msg_sender = MagicMock()
        return comp

    def test_start_sends_value(self):
        comp = self._dsa()
        comp.start()
        assert comp.current_value in d3
        comp._msg_sender.assert_called()
        msg = comp._msg_sender.call_args[0][2]
        assert msg.type == "dsa_value"

    def test_cycle_on_neighbor_value(self):
        comp = self._dsa()
        comp.start()
        from pydcop_tpu.infrastructure.agent_algorithms import DsaMessage

        comp.on_message("v2", DsaMessage(comp.current_value), 0)
        # With probability 1 and a conflicting neighbor value, B changes
        assert comp.cycle_count == 1

    def test_isolated_variable_finishes(self):
        v = Variable("x", d3)
        graph = chg.build_computation_graph(variables=[v], constraints=[])
        algo = AlgorithmDef.build_with_default_param("dsa", {}, "min")
        comp = DsaComputation(ComputationDef(graph.nodes[0], algo))
        comp._msg_sender = MagicMock()
        finished = []
        comp._on_finish_cb = lambda c: finished.append(c.name)
        comp.start()
        assert finished == ["x"]
        assert not comp.is_running


class TestMgmComputation:
    def test_two_phase_round(self):
        v1 = Variable("v1", d3)
        v2 = Variable("v2", d3)
        c1 = constraint_from_str("c1", "1 if v1 == v2 else 0", [v1, v2])
        graph = chg.build_computation_graph(
            variables=[v1, v2], constraints=[c1])
        algo = AlgorithmDef.build_with_default_param("mgm", {}, "min")
        node = next(n for n in graph.nodes if n.name == "v1")
        comp = MgmComputation(ComputationDef(node, algo))
        comp._msg_sender = MagicMock()
        comp.start()
        from pydcop_tpu.infrastructure.agent_algorithms import (
            MgmGainMessage,
            MgmValueMessage,
        )

        comp.on_message("v2", MgmValueMessage(comp.current_value), 0)
        # After receiving all values, a gain message must have been sent:
        types = [
            c[0][2].type for c in comp._msg_sender.call_args_list
        ]
        assert "mgm_gain" in types
        # Deliver neighbor gain lower than ours -> we change value
        comp.on_message("v2", MgmGainMessage(-1.0, 0.5), 0)
        assert comp.cycle_count >= 1


class TestDynamicMaxSum:
    """Dynamic MaxSum computations (reference maxsum_dynamic.py),
    driven directly with mocked senders."""

    def _defs(self, algo_name="maxsum_dynamic"):
        v1 = Variable("v1", d3)
        v2 = Variable("v2", d3)
        c1 = constraint_from_str("c1", "abs(v1 - v2)", [v1, v2])
        graph = fg.build_computation_graph(
            variables=[v1, v2], constraints=[c1])
        algo = AlgorithmDef.build_with_default_param(algo_name, {}, "min")
        return (
            {n.name: ComputationDef(n, algo) for n in graph.nodes},
            (v1, v2, c1),
        )

    def test_change_function_same_scope(self):
        from pydcop_tpu.infrastructure.agent_algorithms import (
            DynamicFunctionFactorComputation,
        )

        defs, (v1, v2, c1) = self._defs()
        fc = DynamicFunctionFactorComputation(defs["c1"])
        fc._msg_sender = MagicMock()
        fc.start()
        new_c = constraint_from_str("c1", "(v1 + v2) * 2", [v1, v2])
        fc.change_factor_function(new_c)
        assert fc.factor is new_c
        # Costs computed after the swap use the new function:
        costs = factor_costs_for_var(fc.factor, v1, {}, "min")
        assert costs == {0: 0, 1: 2, 2: 4}

    def test_change_function_different_scope_raises(self):
        from pydcop_tpu.infrastructure.agent_algorithms import (
            DynamicFunctionFactorComputation,
        )

        defs, (v1, v2, c1) = self._defs()
        fc = DynamicFunctionFactorComputation(defs["c1"])
        v3 = Variable("v3", d3)
        bad = constraint_from_str("c1", "v1 + v3", [v1, v3])
        with pytest.raises(ValueError):
            fc.change_factor_function(bad)

    def test_read_only_factor_slices_on_sensor_values(self):
        from pydcop_tpu.dcop.objects import ExternalVariable
        from pydcop_tpu.infrastructure.agent_algorithms import (
            FactorWithReadOnlyVariableComputation,
        )

        v1 = Variable("v1", d3)
        e1 = ExternalVariable("e1", d3, value=0)
        rule = constraint_from_str("r1", "v1 * e1", [v1, e1])
        graph = fg.build_computation_graph(
            variables=[v1], constraints=[rule])
        algo = AlgorithmDef.build_with_default_param(
            "maxsum_dynamic", {}, "min")
        comp_def = ComputationDef(
            next(n for n in graph.nodes if n.name == "r1"), algo)
        fc = FactorWithReadOnlyVariableComputation(
            comp_def, relation=rule, read_only_variables=[e1])
        fc._msg_sender = MagicMock()
        # Before sensor values arrive: neutral relation over v1 only.
        assert fc.neighbors == ["v1"]
        assert fc.factor(v1=2) == 0
        fc.start()
        # Subscription message went out as a plain (non-cycle) message:
        subs = [
            c[0] for c in fc._msg_sender.call_args_list
            if c[0][2].type == "subscribe"
        ]
        assert [s[1] for s in subs] == ["e1"]
        # Sensor reports e1=2: relation becomes v1*2.
        fc.on_message("e1", Message("external_value", 2), 0)
        assert fc.factor(v1=1) == 2
        assert fc.factor.scope_names == ["v1"]

    def test_dynamic_factor_scope_change_notifies_variables(self):
        from pydcop_tpu.infrastructure.agent_algorithms import (
            DynamicFactorComputation,
        )

        defs, (v1, v2, c1) = self._defs()
        fc = DynamicFactorComputation(defs["c1"])
        fc._msg_sender = MagicMock()
        fc.start()
        v3 = Variable("v3", d3)
        new_c = constraint_from_str("c1", "v1 + v3", [v1, v3])
        fc.change_factor_function(new_c)
        assert set(fc.neighbors) == {"v1", "v3"}
        plain = [
            (c[0][1], c[0][2].type)
            for c in fc._msg_sender.call_args_list
            if c[0][2].type in ("maxsum_add", "maxsum_remove")
        ]
        assert ("v2", "maxsum_remove") in plain
        assert ("v3", "maxsum_add") in plain

    def test_dynamic_factor_slices_external_at_init(self):
        from pydcop_tpu.dcop.objects import ExternalVariable
        from pydcop_tpu.infrastructure.agent_algorithms import (
            DynamicFactorComputation,
        )

        v1 = Variable("v1", d3)
        e1 = ExternalVariable("e1", d3, value=1)
        rule = constraint_from_str("r1", "v1 * e1", [v1, e1])
        graph = fg.build_computation_graph(
            variables=[v1], constraints=[rule])
        algo = AlgorithmDef.build_with_default_param(
            "maxsum_dynamic", {}, "min")
        comp_def = ComputationDef(
            next(n for n in graph.nodes if n.name == "r1"), algo)
        fc = DynamicFactorComputation(comp_def)
        assert fc.neighbors == ["v1"]
        assert fc.factor(v1=2) == 2
        # Sensor change re-slices:
        fc._msg_sender = MagicMock()
        fc.on_message("e1", Message("external_value", 2), 0)
        assert fc.factor(v1=2) == 4

    def test_dynamic_variable_add_remove(self):
        from pydcop_tpu.infrastructure.agent_algorithms import (
            DynamicFactorVariableComputation,
        )

        defs, (v1, v2, c1) = self._defs()
        vc = DynamicFactorVariableComputation(defs["v1"])
        vc._msg_sender = MagicMock()
        vc.start()
        assert vc.neighbors == ["c1"]
        vc.on_message("c2", Message("maxsum_add", "c2"), 0)
        assert set(vc.neighbors) == {"c1", "c2"}
        vc.on_message("c1", Message("maxsum_remove", "c1"), 0)
        assert vc.neighbors == ["c2"]
        with pytest.raises(ValueError):
            vc.on_message("c9", Message("maxsum_remove", "c9"), 0)

    def test_solve_on_device_matches_maxsum(self):
        from pydcop_tpu.api import solve
        from pydcop_tpu.dcop.dcop import DCOP

        v1 = Variable("v1", d3)
        v2 = Variable("v2", d3)
        c1 = constraint_from_str("c1", "abs(v1 - v2)", [v1, v2])
        dcop = DCOP("t")
        dcop.add_constraint(c1)
        r1 = solve(dcop, "maxsum_dynamic", max_cycles=30)
        r2 = solve(dcop, "maxsum", max_cycles=30)
        assert r1["cost"] == pytest.approx(r2["cost"])


class TestDynamicMaxSumRegressions:
    """Regressions found in review: BSP stall on factor removal,
    external-variable handling in plain vs dynamic maxsum."""

    def test_remove_completes_stalled_cycle(self):
        from pydcop_tpu.infrastructure.agent_algorithms import (
            DynamicFactorVariableComputation,
        )

        v1 = Variable("v1", d3)
        v2 = Variable("v2", d3)
        c1 = constraint_from_str("c1", "abs(v1 - v2)", [v1, v2])
        c2 = constraint_from_str("c2", "v1 + v2", [v1, v2])
        graph = fg.build_computation_graph(
            variables=[v1, v2], constraints=[c1, c2])
        algo = AlgorithmDef.build_with_default_param(
            "maxsum_dynamic", {}, "min")
        node = next(n for n in graph.nodes if n.name == "v1")
        vc = DynamicFactorVariableComputation(ComputationDef(node, algo))
        vc._msg_sender = MagicMock()
        vc.start()
        # c2's cycle-0 message arrives; cycle waits on c1.
        vc.on_message(
            "c2", Message("_cycle", (0, MaxSumMessage({0: 0, 1: 0, 2: 0}))),
            0,
        )
        assert vc.cycle_id == 0
        # c1 leaves: the shrunk neighbor set makes cycle 0 complete.
        vc.on_message("c1", Message("maxsum_remove", "c1"), 0)
        assert vc.cycle_id == 1
        # Subsequent cycles from c2 keep flowing without skew errors.
        vc.on_message(
            "c2", Message("_cycle", (1, MaxSumMessage({0: 0, 1: 0, 2: 0}))),
            0,
        )
        assert vc.cycle_id == 2

    def test_device_solve_slices_external_variables(self):
        from pydcop_tpu.api import solve
        from pydcop_tpu.dcop.dcop import DCOP
        from pydcop_tpu.dcop.objects import ExternalVariable

        v1 = Variable("v1", d3)
        v2 = Variable("v2", d3)
        e1 = ExternalVariable("e1", d3, value=2)
        dcop = DCOP("t")
        dcop.add_external_variable(e1)
        dcop.add_constraint(
            constraint_from_str("c1", "v1 * e1 + abs(v1 - v2)",
                                [v1, v2, e1]))
        res = solve(dcop, "maxsum_dynamic", max_cycles=50)
        # With e1=2: cost = 2*v1 + |v1-v2|, optimum v1=v2=0.
        assert res["assignment"] == {"v1": 0, "v2": 0}

    def test_plain_maxsum_rejects_external_variables(self):
        from pydcop_tpu.api import solve
        from pydcop_tpu.dcop.dcop import DCOP
        from pydcop_tpu.dcop.objects import ExternalVariable

        from pydcop_tpu.dcop.objects import AgentDef

        v1 = Variable("v1", d3)
        e1 = ExternalVariable("e1", d3, value=1)
        dcop = DCOP("t")
        dcop.add_external_variable(e1)
        dcop.add_constraint(
            constraint_from_str("c1", "v1 * e1", [v1, e1]))
        dcop.add_agents([AgentDef("a1"), AgentDef("a2")])
        with pytest.raises(ValueError, match="maxsum_dynamic"):
            solve(dcop, "maxsum", max_cycles=10)
        with pytest.raises(ValueError, match="maxsum_dynamic"):
            solve(dcop, "maxsum", backend="thread", timeout=2)


class TestNcbbGreedyCosts:
    def test_thread_greedy_counts_own_costs(self):
        from pydcop_tpu.api import solve
        from pydcop_tpu.dcop.dcop import DCOP
        from pydcop_tpu.dcop.objects import VariableWithCostFunc

        from pydcop_tpu.dcop.objects import AgentDef

        d2 = Domain("d", "", [0, 1])
        v1 = Variable("v1", d2)
        v2 = VariableWithCostFunc("v2", d2, cost_func=lambda x: 10 * x)
        dcop = DCOP("t")
        dcop.add_variable(v2)
        dcop.add_constraint(
            constraint_from_str("c1", "1 - abs(v1 - v2)", [v1, v2]))
        dcop.add_agents([AgentDef("a1"), AgentDef("a2")])
        res = solve(dcop, "ncbb", backend="thread", timeout=5)
        # The search must count v2's own cost: the optimum is
        # v1=1, v2=0 (constraint 0, own cost 0) — ignoring own costs
        # would allow v2=1 assignments whose true cost is >= 10.
        # (Before the SEARCH phase landed this asserted the INIT
        # greedy's 1.0; search now reaches the optimum.)
        assert res["cost"] == pytest.approx(0.0)

"""HTTP front end for the solve service (stdlib-only).

Extends the PR-5 telemetry endpoint
(:class:`~pydcop_tpu.observability.server.TelemetryServer`) with the
request plane, so one port serves the solve API *and* its own
telemetry:

- ``POST /solve`` — body ``{"dcop": "<dcop yaml>", "params": {...},
  "wait": bool, "timeout": s, "deadline_s": s}``.  Returns 202 + a
  request id (poll ``/result/<id>``), or the finished result directly
  with ``"wait": true`` (200; 202 + id if the wait timed out).
  ``deadline_s`` is a freshness budget: work still queued past it is
  dropped by the scheduler (504, ``rejected_deadline``).  Errors:
  400 malformed body/problem/params (a malformed ``timeout`` or
  ``deadline_s`` is a 400, never silently coerced), 429 queue past
  high-water (back off and retry), 503 dispatch breaker open.
- ``GET /result/<id>`` — 200 + result when done, 202 while pending,
  504 + result when the deadline expired it, 404 unknown id.
- ``GET /stats`` — the service's dispatch/queue/breaker ledger.
- ``GET /metrics`` / ``/healthz`` / ``/events`` — mounted unchanged
  from the telemetry server; ``/healthz`` additionally reflects the
  serving state (open dispatch breaker → ``failing`` → 503).
- Stateful sessions (docs/sessions.md): ``POST /session`` opens a
  long-lived solve (201 + session_id/trace_id),
  ``PATCH /session/<id>/events`` streams scenario events into it
  (the 200 is journal-durable like a submit's 202),
  ``GET /session/<id>`` polls status, ``GET /session/<id>/events``
  streams anytime assignment/cost per segment (SSE), and
  ``DELETE /session/<id>`` closes with the final result.

curl examples live in docs/serving.md and docs/sessions.md.
"""

import contextlib
import json
import logging
import math
import queue
from typing import Any, Dict, Optional

from pydcop_tpu.observability import fleettrace
from pydcop_tpu.observability.server import (
    TelemetryServer,
    _Handler,
    get_health_provider,
    set_health_provider,
)
from pydcop_tpu.observability.trace import tracer
from pydcop_tpu.serving.admission import AdmissionRejected
from pydcop_tpu.serving.service import SolveService, WidthRejected
from pydcop_tpu.serving.sessions import (
    SessionClosed,
    StaleEpoch,
    scenario_yaml_to_events,
)

logger = logging.getLogger("pydcop.serving.http")

# Request bodies are small YAML problems; refuse anything huge before
# reading it (a misbehaving client must not balloon the process).
MAX_BODY_BYTES = 8 << 20


def _positive_float(value: Any, name: str) -> float:
    """Strict wire-field validation: a finite number > 0, or
    ValueError.  Non-finite values are rejected — ``timeout: inf``
    would pin one of the server's handler threads forever."""
    try:
        out = float(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"{name} must be a number of seconds, got {value!r}")
    if not math.isfinite(out) or not out > 0:
        raise ValueError(
            f"{name} must be a finite number > 0, got {out}")
    return out


def _result_code(result: Dict[str, Any]) -> int:
    """HTTP status for a terminal result body: 504 for a
    deadline-expired request, 400 for a dispatch-time width rejection
    (the client sent a problem exact inference cannot afford — a
    client fault, not a server one), 200 otherwise (a generic ERROR
    result is a well-formed 200 reply whose body says the solve
    failed)."""
    if result.get("status") == "EXPIRED":
        return 504
    if result.get("status_detail") == "rejected_width":
        return 400
    return 200


class _ServeHandler(_Handler):
    """Telemetry routes + the solve request plane."""

    def _json(self, code: int, payload: Dict[str, Any],
              close: bool = False):
        self._reply(code, json.dumps(payload, default=str).encode(),
                    "application/json", close=close)

    def _read_json_body(self) -> Optional[Dict[str, Any]]:
        """Read + decode the request's JSON object body; replies the
        4xx itself and returns None on failure (callers just
        return)."""
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = 0
        if length <= 0 or length > MAX_BODY_BYTES:
            self._json(400, {"error": "body required (JSON, "
                                      f"<= {MAX_BODY_BYTES} bytes)"},
                       close=True)
            return None
        raw = self.rfile.read(length)
        try:
            body = json.loads(raw)
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
        except ValueError as exc:
            self._json(400, {"error": f"bad request body: {exc}"})
            return None
        return body

    def do_GET(self):  # noqa: N802 — stdlib name
        path = self.path.split("?", 1)[0]
        service = self.telemetry.service
        if path.startswith("/session/"):
            rest = path[len("/session/"):]
            if rest.endswith("/events"):
                self._stream_session(rest[:-len("/events")])
                return
            try:
                self._json(200, service.sessions.status(rest))
            except KeyError:
                self._json(404, {"error": f"unknown session {rest!r}"})
            return
        if path.startswith("/result/"):
            rid = path[len("/result/"):]
            # Both lookups can KeyError: the id may be unknown, or
            # the entry may be evicted between the two calls
            # (result() pending -> completion -> a concurrent
            # submit's retention prune).  Either way: 404.
            try:
                result = service.result(rid)
                if result is None:
                    self._json(202, {"id": rid,
                                     "status": service.status(rid),
                                     "trace_id": service.trace_id(rid)})
                    return
            except KeyError:
                self._json(404, {"error": f"unknown request {rid!r}"})
                return
            self._json(_result_code(result), result)
        elif path == "/stats":
            self._json(200, service.stats())
        else:
            super().do_GET()

    def do_POST(self):  # noqa: N802 — stdlib name
        path = self.path.split("?", 1)[0]
        if path == "/session":
            self._open_session()
            return
        if path.startswith("/admin/"):
            self._admin(path[len("/admin/"):])
            return
        if path != "/solve":
            # Replying without reading the body would leave it on the
            # socket and corrupt the next keep-alive request (the
            # handler speaks HTTP/1.1): advertise-and-close on every
            # error path that skips the read.
            self._json(404, {"error": "unknown path"}, close=True)
            return
        body = self._read_json_body()
        if body is None:
            return
        yaml_src = body.get("dcop")
        if not isinstance(yaml_src, str) or not yaml_src.strip():
            self._json(400, {"error": "bad request body: body needs "
                                      "a 'dcop' key holding the "
                                      "problem as a dcop yaml string"})
            return
        service = self.telemetry.service
        # Wire-level fields validate BEFORE submit: a malformed
        # ``timeout`` used to be silently coerced to 30.0 by a bare
        # except — a typo'd client ran with a default it never chose.
        # Now it is a 400 (``rejected_bad_request`` in the ledger),
        # and because nothing was submitted yet there is no orphaned
        # accepted request behind the rejection.
        try:
            timeout = _positive_float(
                body.get("timeout", 30.0), "timeout")
            deadline_s = body.get("deadline_s")
            if deadline_s is not None:
                deadline_s = _positive_float(deadline_s, "deadline_s")
            # Caller-supplied id (the fleet router mints fleet-unique
            # ids so /result polls can be pinned to the owning
            # replica; worker-local counters would collide across a
            # fleet).  Validated like every other wire field.
            request_id = body.get("request_id")
            if request_id is not None and (
                    not isinstance(request_id, str)
                    or not request_id.strip()):
                raise ValueError(
                    f"request_id must be a non-empty string, got "
                    f"{request_id!r}")
        except ValueError as exc:
            service.record_bad_request()
            self._json(400, {"error": f"bad request body: {exc}"})
            return
        # The fleet router's wire-propagated trace context (ISSUE 20):
        # adopting it makes this replica's serve_* spans part of the
        # router's admission trace in the fleet collector.
        ctx = fleettrace.decode_headers(self.headers)
        try:
            from pydcop_tpu.dcop.yamldcop import load_dcop

            dcop = load_dcop(yaml_src)
            rid = service.submit(dcop, params=body.get("params"),
                                 request_id=request_id,
                                 deadline_s=deadline_s,
                                 trace_id=(ctx.trace_id if ctx
                                           else None))
        except AdmissionRejected as exc:
            self._json(exc.http_status, {
                "error": str(exc),
                "status": "rejected",
                "retry": exc.http_status == 429,
            })
            return
        except WidthRejected as exc:
            # ``algo:"dpop"`` on a problem whose UTIL hypercubes bust
            # the element cap even after CEC shrinkage.  The width
            # check runs on the submitting thread before anything is
            # queued, so this is a clean structured 400: no orphaned
            # request, nothing fed to the admission breaker, and the
            # body tells the client exactly how far over the cap the
            # problem is (retrying the same shape cannot help).
            self._json(400, {
                "error": str(exc),
                "status": exc.status,
                "max_elements": exc.max_elements,
                "max_elements_cap": exc.cap,
                "retry": False,
            })
            return
        except RuntimeError as exc:
            # Server-side submit failure (journal append I/O): the
            # request was valid and the fault is ours — a 400 would
            # tell a well-behaved client to stop retrying.
            self._json(500, {"error": f"internal error: {exc}"})
            return
        except Exception as exc:  # noqa: BLE001 — malformed problem
            self._json(400, {"error": f"bad problem: {exc}"})
            return
        if body.get("wait"):
            result = service.result(rid, wait=timeout)
            if result is not None:
                self._json(_result_code(result), result)
                return
            # Fell through the wait window: hand back the id.
        # The trace_id rides every ack: the client holds the handle
        # that `pydcop trace query --request` takes without another
        # round trip (a request may be gone from retention by the
        # time anyone wants its trace).
        try:
            trace_id = service.trace_id(rid)
        except KeyError:  # evicted already (tiny result_keep)
            trace_id = None
        self._json(202, {"id": rid, "status": "queued",
                         "trace_id": trace_id,
                         "result_url": f"/result/{rid}"})

    # -- migration admin plane (docs/serving.md) ----------------------- #

    def _admin(self, op: str):
        """``POST /admin/<op>_session`` — the worker side of live
        session migration (docs/serving.md).  The fleet router drives
        these; they are same-box trust, like ``/solve``:

        - ``export_session`` — drain + checkpoint the session, freeze
          it MIGRATING, return the portable bundle (200).
        - ``import_session`` — journal + rebuild a bundle's session
          here (201).  The import journals *before* it rebuilds, so a
          crash mid-import leaves a replayable journal, never a lost
          session.
        - ``retire_session`` — close out a MIGRATING session on the
          source once the target owns it (200, idempotent).
        - ``resume_session`` — roll a MIGRATING session back to OPEN
          after a failed import (200).
        - ``fence_session`` — revoke this replica's stale copy of a
          session whose ownership epoch moved on while it was
          partitioned (200, idempotent; 409 when the fence itself is
          stale).
        """
        if op == "trace_collector":
            # ``POST /admin/trace_collector`` — the router pushes its
            # fleet-collector address here (at fleet start, after a
            # replica restart, on a --join) so this process's span
            # shipper knows where completed spans go; ``enable:
            # false`` detaches it (the perf-smoke pairwise gate
            # toggles tracing at runtime this way).
            body = self._read_json_body()
            if body is None:
                return
            try:
                out = fleettrace.configure_shipper(
                    body.get("url"),
                    source=str(body.get("source") or "worker"),
                    enable=bool(body.get("enable", True)))
            except Exception as exc:  # noqa: BLE001 — admin answers
                self._json(500, {"error": f"internal error: {exc}"})
                return
            self._json(200, out)
            return
        if op not in ("export_session", "import_session",
                      "retire_session", "resume_session",
                      "fence_session"):
            self._json(404, {"error": "unknown path"}, close=True)
            return
        body = self._read_json_body()
        if body is None:
            return
        service = self.telemetry.service
        # Migration/fence admin calls are router-driven: the fleet
        # context on them tags this replica's side of the hop (the
        # import/export spans inside the session manager record
        # under it via the thread-bound args).
        ctx = fleettrace.decode_headers(self.headers)
        admin_ctx = (tracer.context(trace_ids=[ctx.trace_id])
                     if ctx is not None and tracer.active
                     else contextlib.nullcontext())
        try:
            with admin_ctx:
                if op == "import_session":
                    from pydcop_tpu.serving import migration

                    sess = migration.install_bundle(
                        service.sessions, body)
                    self._json(201, {"session_id": sess.id,
                                     "trace_id": sess.trace_id,
                                     "seq": sess.seq,
                                     "status": sess.status})
                    return
                sid = body.get("session_id")
                if not isinstance(sid, str) or not sid.strip():
                    raise ValueError(
                        "body needs a 'session_id' string")
                if op == "export_session":
                    wait = _positive_float(
                        body.get("wait", 60.0), "wait")
                    out = service.sessions.export_session(
                        sid, wait=wait)
                elif op == "retire_session":
                    out = service.sessions.retire_session(
                        sid, moved_to=body.get("moved_to"))
                elif op == "fence_session":
                    out = service.sessions.fence_session(
                        sid, int(body.get("epoch") or 0))
                else:  # resume_session
                    out = service.sessions.resume_session(sid)
                self._json(200, out)
        except KeyError as exc:
            self._json(404, {"error": f"unknown session: {exc}"})
        except StaleEpoch as exc:
            self._json(409, {"error": str(exc), "stale_epoch": True,
                             "session_epoch": exc.session_epoch,
                             "request_epoch": exc.request_epoch})
        except SessionClosed as exc:
            self._json(409, {"error": str(exc)})
        except TimeoutError as exc:
            self._json(504, {"error": str(exc)})
        except ValueError as exc:
            service.record_bad_request()
            self._json(400, {"error": f"bad request body: {exc}"})
        except Exception as exc:  # noqa: BLE001 — admin must answer
            logger.warning("admin %s failed: %s", op, exc)
            self._json(500, {"error": f"internal error: {exc}"})

    # -- stateful sessions (docs/sessions.md) -------------------------- #

    def _open_session(self):
        """``POST /session`` — body ``{"dcop": yaml, "params":
        {...}}``: opens a stateful solve whose engine lives across
        requests.  201 + session_id/trace_id; the session starts
        converging immediately and streams anytime results on
        ``GET /session/<id>/events``."""
        body = self._read_json_body()
        if body is None:
            return
        yaml_src = body.get("dcop")
        if not isinstance(yaml_src, str) or not yaml_src.strip():
            self._json(400, {"error": "bad request body: body needs "
                                      "a 'dcop' key holding the "
                                      "problem as a dcop yaml string"})
            return
        service = self.telemetry.service
        try:
            from pydcop_tpu.dcop.yamldcop import load_dcop

            dcop = load_dcop(yaml_src)
            ctx = fleettrace.decode_headers(self.headers)
            sess = service.sessions.open(
                dcop, params=body.get("params"),
                session_id=body.get("session_id"),
                trace_id=ctx.trace_id if ctx else None)
        except AdmissionRejected as exc:
            self._json(exc.http_status, {
                "error": str(exc), "status": "rejected",
                "retry": exc.http_status == 429,
            })
            return
        except RuntimeError as exc:
            self._json(500, {"error": f"internal error: {exc}"})
            return
        except Exception as exc:  # noqa: BLE001 — malformed problem
            service.record_bad_request()
            self._json(400, {"error": f"bad problem: {exc}"})
            return
        self._json(201, {
            "session_id": sess.id,
            "trace_id": sess.trace_id,
            "status": sess.status,
            "events_url": f"/session/{sess.id}/events",
        })

    def do_PATCH(self):  # noqa: N802 — stdlib name
        """``PATCH /session/<id>/events`` — body ``{"events": [...]}``
        (wire actions) or ``{"scenario": "<scenario yaml>"}``; with
        ``"wait": true`` the reply carries the post-event segment
        result.  The 200 is durable: the batch is journaled before
        the ack."""
        path = self.path.split("?", 1)[0]
        if not (path.startswith("/session/")
                and path.endswith("/events")):
            self._json(404, {"error": "unknown path"}, close=True)
            return
        sid = path[len("/session/"):-len("/events")]
        body = self._read_json_body()
        if body is None:
            return
        service = self.telemetry.service
        # Wire-level parsing FIRST, in its own guard: a malformed
        # scenario yaml raises KeyError('type'/'id') from the loader,
        # which the unknown-session handler below would otherwise
        # mistranslate into a 404 for a perfectly live session.
        try:
            events = body.get("events")
            if events is None and body.get("scenario"):
                events = scenario_yaml_to_events(body["scenario"])
            wait = None
            if body.get("wait"):
                wait = _positive_float(
                    body.get("timeout", 30.0), "timeout")
            epoch = body.get("epoch")
            if epoch is not None:
                epoch = int(epoch)
        except Exception as exc:  # noqa: BLE001 — malformed body
            service.record_bad_request()
            self._json(400, {"error": f"bad events: {exc}"})
            return
        ctx = fleettrace.decode_headers(self.headers)
        try:
            out = service.sessions.apply_events(
                sid, events, wait=wait, epoch=epoch,
                trace_id=ctx.trace_id if ctx else None)
        except KeyError:
            self._json(404, {"error": f"unknown session {sid!r}"})
            return
        except StaleEpoch as exc:
            # Structured 409 (ISSUE 19): the fenced/stale side MUST be
            # machine-distinguishable from an ordinary closed-session
            # race — clients re-resolve ownership through the router
            # instead of retrying here.
            self._json(409, {"error": str(exc), "stale_epoch": True,
                             "session_epoch": exc.session_epoch,
                             "request_epoch": exc.request_epoch})
            return
        except SessionClosed as exc:
            self._json(409, {"error": str(exc)})
            return
        except RuntimeError as exc:
            self._json(500, {"error": f"internal error: {exc}"})
            return
        except Exception as exc:  # noqa: BLE001 — malformed events
            service.record_bad_request()
            self._json(400, {"error": f"bad events: {exc}"})
            return
        self._json(200, out)

    def do_DELETE(self):  # noqa: N802 — stdlib name
        """``DELETE /session/<id>`` — close the session; 200 + the
        final result (idempotent: a second DELETE returns the same
        final)."""
        path = self.path.split("?", 1)[0]
        if not path.startswith("/session/"):
            self._json(404, {"error": "unknown path"}, close=True)
            return
        sid = path[len("/session/"):]
        service = self.telemetry.service
        try:
            final = service.sessions.close(sid)
        except KeyError:
            self._json(404, {"error": f"unknown session {sid!r}"})
            return
        except SessionClosed as exc:
            self._json(409, {"error": str(exc)})
            return
        except TimeoutError as exc:
            self._json(504, {"error": str(exc)})
            return
        except Exception as exc:  # noqa: BLE001 — close must answer
            self._json(500, {"error": f"internal error: {exc}"})
            return
        self._json(200, final)

    def _stream_session(self, sid: str):
        """``GET /session/<id>/events`` — per-session SSE: the latest
        segment event replays on connect, then every segment /
        terminal event streams as it lands.  The stream ends when the
        session reaches a terminal state."""
        service = self.telemetry.service
        try:
            q = service.sessions.subscribe(sid)
        except KeyError:
            self._json(404, {"error": f"unknown session {sid!r}"})
            return
        # Router-proxied streams carry the fleet context: the attach
        # instant is what lets forensics show WHO was watching the
        # session while the events under inspection streamed.
        ctx = fleettrace.decode_headers(self.headers)
        if ctx is not None and tracer.active:
            tracer.instant("session_stream_attach", "serving",
                           session=sid, trace_id=ctx.trace_id)
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.end_headers()
        try:
            while not self.telemetry._stopping.is_set():
                try:
                    event = q.get(timeout=1.0)
                except queue.Empty:
                    self.wfile.write(b": keepalive\n\n")
                    self.wfile.flush()
                    continue
                self._write_event(event)
                if event.get("status") in ("CLOSED", "ERROR",
                                           "REPLAYABLE", "MIGRATED"):
                    break
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client went away — normal SSE termination
        finally:
            service.sessions.unsubscribe(sid, q)


class ServeFrontEnd(TelemetryServer):
    """One HTTP server binding the solve API + telemetry routes.

    Owns neither the service's lifecycle nor the registry — start the
    :class:`SolveService` first (or use :func:`pydcop_tpu.api.serve`,
    which wires both).  While running, the service's health summary
    feeds the process-wide ``/healthz`` provider so an open dispatch
    breaker turns the probe 503.
    """

    handler_class = _ServeHandler

    def __init__(self, service: SolveService, port: int = 0,
                 host: str = "127.0.0.1", registry=None):
        super().__init__(port=port, host=host, registry=registry)
        self.service = service
        self._prior_provider = None

    def start(self) -> "ServeFrontEnd":
        super().start()
        # Save/restore, don't clobber: a process embedding the front
        # end next to a health-monitored thread run must get its
        # provider back when the front end stops.
        self._prior_provider = get_health_provider()
        set_health_provider(self.service.health_summary)
        return self

    def stop(self):
        set_health_provider(self._prior_provider)
        self._prior_provider = None
        super().stop()

"""Multi-tenant solve service: the request plane over the device engine.

One ``api.solve`` call solves one DCOP; production traffic is
millions of small problems.  This package turns the engine into a
throughput service (docs/serving.md):

- :mod:`.service` — :class:`SolveService`: bounded request queue,
  per-request compile (hitting the PR-3 structure cache), result
  store with latency accounting, request-plane telemetry
  (``pydcop_requests_total{status}``,
  ``pydcop_request_latency_seconds``, batch-occupancy gauge);
- :mod:`.scheduler` — the batching scheduler: drains the queue,
  coalesces a batch window, dispatches each structure bin as ONE
  vmapped device program (engine/batch.run_stacked, padded up the
  bin-size ladder so ragged batches reuse compiled programs);
- :mod:`.binning` — structure-signature bin keys (two structures
  never share an *exact* dispatch; same-structure requests coalesce)
  plus the envelope tier: shape-envelope keys, cell accounting and
  the pack-vs-solo cost model that lets *different*-structure
  singletons share a mask-padded dispatch with bit-identical
  results (docs/serving.md "Envelope batching");
- :mod:`.admission` — backpressure (queue high-water → 429) and the
  dispatch circuit breaker (repeated engine failure → 503);
- :mod:`.journal` — the durable request journal: length-prefixed,
  crc-checksummed on-disk records appended before every 202, torn
  tails truncated and unfinished requests replayed on a
  ``--recover`` start (kill -9 loses zero acknowledged requests);
- :mod:`.http` — stdlib HTTP front end (``POST /solve``,
  ``GET /result/<id>``, ``GET /stats``) mounting the PR-5 telemetry
  routes (``/metrics``, ``/healthz``, ``/events``) alongside;
- :mod:`.router` — fleet-scale serving (docs/serving.md
  "Fleet-scale serving"): N worker replicas (each a full service in
  its own process with its own journal segment) behind a
  structure-affinity router — rendezvous hashing on the
  admission-time structure key (:func:`.binning.affinity_key`),
  least-loaded spillover, breaker-aware shedding, heartbeat death
  detection with journal handoff to the restarted worker, and the
  shared persistent AOT compile cache (engine/aotcache.py);
- :mod:`.sessions` — stateful solve sessions (docs/sessions.md):
  ``POST /session`` opens a solve backed by one warm
  ``DynamicMaxSumEngine``, ``PATCH /session/<id>/events`` streams
  scenario events applied between engine segments (in-shape edits =
  zero recompiles, messages warm-start from the pre-event fixpoint,
  decimation clamps release on touched variables only),
  ``GET /session/<id>/events`` (SSE) streams anytime results, and
  the journal replays WHOLE sessions after a crash.

Entry points: ``pydcop serve`` (commands/serve.py) and
:func:`pydcop_tpu.api.serve`.
"""

from pydcop_tpu.serving.admission import (  # noqa: F401
    AdmissionController,
    AdmissionPolicy,
    AdmissionRejected,
    QueueFull,
    ServiceUnavailable,
)
from pydcop_tpu.serving.journal import (  # noqa: F401
    RequestJournal,
)
from pydcop_tpu.serving.router import (  # noqa: F401
    FleetRouter,
    RouterFrontEnd,
)
from pydcop_tpu.serving.service import (  # noqa: F401
    SolveRequest,
    SolveService,
)
from pydcop_tpu.serving.sessions import (  # noqa: F401
    SessionClosed,
    SessionLimit,
    SessionManager,
    SolveSession,
)

"""Batched multi-instance solving: many DCOPs in ONE XLA program.

A capability the reference architecture cannot express: its benchmark
sweeps (`pydcop batch`) run one subprocess per instance
(pydcop/commands/batch.py), paying process + solve overhead per run.
On device, same-shaped compiled graphs stack into batched arrays and
`jax.vmap` turns the whole MaxSum solve into a single program over the
instance axis — N problems cost barely more than one (the MXU/VPU work
batches; the host launches once).

Shape contract: every instance must compile to identical array shapes
(same variable count, same dmax, same bucket layout) — exactly what
seeded generator sweeps produce (same config, different seeds or cost
tables).  A shape mismatch raises instead of silently padding, so the
caller controls the batching granularity.

This module is ALSO the serving hot path (pydcop_tpu/serving/): the
request scheduler stacks same-structure-bin requests and dispatches
them through :func:`run_stacked`.  Two serving-driven extensions:

- **Padding to bin sizes.** A jitted batched program re-traces per
  batch size, so a scheduler dispatching raw batch sizes 3, 5, 7, 6 …
  would compile a fresh program per straggler count.  ``pad_to_bins``
  rounds the stack up to a fixed ladder of sizes (duplicating the
  last instance; padded lanes are computed and discarded), bounding
  the number of compiled programs per structure to ``len(bins)``.

- **Honest padding accounting.** Padded lanes are wasted device work,
  so every padded dispatch reports ``pad_fraction`` (padded lanes /
  batch size) in ``DeviceRunResult.metrics`` — the serving
  batch-occupancy telemetry reads it instead of guessing.
"""

import contextlib
import functools
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.engine.compile import (
    CompiledFactorGraph,
    FactorGraphMeta,
    compile_dcop,
)
from pydcop_tpu.engine.runner import DeviceRunResult, timed_jit_call
from pydcop_tpu.observability.trace import tracer
from pydcop_tpu.ops import maxsum as maxsum_ops

# Batch-size ladder used when a caller asks for bin padding without
# giving one: powers of two keep the compiled-program count per
# structure logarithmic in the largest batch.
DEFAULT_BIN_SIZES = (1, 2, 4, 8, 16, 32, 64)

# jit-cache warmth per (shape-signature, solver statics) — feeds the
# cold/warm split in timed_jit_call so serving dispatch latencies can
# separate compile stalls from steady-state batches.
_warm: set = set()


def stack_graphs(
    graphs: Sequence[CompiledFactorGraph],
) -> CompiledFactorGraph:
    """Stack same-shaped compiled graphs along a new leading axis."""
    shapes = [
        (g.var_costs.shape,) + tuple(b.costs.shape for b in g.buckets)
        for g in graphs
    ]
    if any(s != shapes[0] for s in shapes):
        raise ValueError(
            "Batched solving requires identical compiled shapes; got "
            f"{sorted(set(shapes))}"
        )
    return jax.tree.map(lambda *xs: jnp.stack(xs), *graphs)


# Pre-promotion private name, kept for external callers.
_stack_graphs = stack_graphs


def bin_size_for(n: int, bin_sizes: Sequence[int]) -> int:
    """Smallest ladder size >= n; n itself when the ladder tops out
    below it (an oversized dispatch compiles once for its exact size
    rather than failing)."""
    for b in sorted(bin_sizes):
        if b >= n:
            return b
    return n


def pad_to_bin(
    graphs: Sequence[CompiledFactorGraph],
    bin_sizes: Sequence[int] = DEFAULT_BIN_SIZES,
) -> Tuple[List[CompiledFactorGraph], int, float]:
    """Pad a graph list up to the next bin size by repeating the last
    instance.  Returns (padded_graphs, n_real, pad_fraction) — padded
    lanes solve a duplicate problem whose results the caller drops.
    """
    n_real = len(graphs)
    if n_real == 0:
        return [], 0, 0.0
    target = bin_size_for(n_real, bin_sizes)
    padded = list(graphs) + [graphs[-1]] * (target - n_real)
    return padded, n_real, (target - n_real) / target


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_cycles", "damping", "damp_vars", "damp_factors",
        "stability", "prune",
    ),
)
def _batched_solve(stacked, *, max_cycles, damping, damp_vars,
                   damp_factors, stability, prune=False):
    """One jitted program per solver-parameter combination (jit's own
    cache keys on the static args), reused across calls — a fresh
    closure per call would retrace and recompile every time.

    ``prune`` threads branch-and-bound pruning into each lane.  Under
    vmap the per-lane phase predicates batch, so the dense/compacted
    alternation degrades toward evaluating both sides more often than
    the solo engine would — the decision consumed here
    (serving/service: prune="auto") was raced on the SOLO path, where
    the win is largest; results are identical either way."""

    def solve_one(graph):
        state, values = maxsum_ops.run_maxsum(
            graph, max_cycles,
            damping=damping,
            damp_vars=damp_vars,
            damp_factors=damp_factors,
            stability=stability,
            stop_on_convergence=False,
            prune=prune,
        )
        return values, state.cycle, state.stable

    return jax.vmap(solve_one)(stacked)


def _shape_signature(stacked: CompiledFactorGraph) -> tuple:
    return (
        (stacked.var_costs.shape,)
        + tuple(b.costs.shape for b in stacked.buckets)
    )


def run_stacked(
    graphs: Sequence[CompiledFactorGraph],
    max_cycles: int = 200,
    damping: float = 0.5,
    damping_nodes: str = "both",
    stability: float = 0.1,
    pad_to_bins: Optional[Sequence[int]] = None,
    prune: bool = False,
) -> Tuple[np.ndarray, np.ndarray, DeviceRunResult]:
    """One device dispatch over a stack of same-shaped compiled graphs.

    The serving hot path: all instances run ``max_cycles`` cycles (no
    convergence stop — a data-dependent loop bound would serialize the
    batch; converged instances freeze via send suppression, so extra
    cycles don't change their assignment).  With ``pad_to_bins`` the
    stack is padded up the bin ladder first (see module docstring).

    Returns ``(values, cycles, batch_result)``: per-instance selected
    value indices / cycle counts for the first ``n_real`` lanes
    (padding lanes already dropped), plus a batch-level
    :class:`DeviceRunResult` whose ``metrics`` carry the dispatch
    accounting — ``batch_size``, ``n_real``, ``pad_fraction``,
    ``cold_start`` — and whose ``assignment`` is empty (a batch has no
    single assignment; decode per instance via each meta).
    """
    if not graphs:
        raise ValueError("run_stacked needs at least one graph")
    n_real = len(graphs)
    pad_fraction = 0.0
    if pad_to_bins is not None:
        graphs, n_real, pad_fraction = pad_to_bin(graphs, pad_to_bins)
    stacked = stack_graphs(graphs)
    statics = dict(
        max_cycles=max_cycles,
        damping=damping,
        damp_vars=damping_nodes in ("vars", "both"),
        damp_factors=damping_nodes in ("factors", "both"),
        stability=stability,
        prune=prune,
    )
    key = (
        "maxsum_batch", len(graphs), _shape_signature(stacked),
        tuple(sorted(statics.items())),
    )
    t0 = time.perf_counter()
    # A batched dispatch IS one engine segment (the whole solve in
    # one program): the span name matches the segmented loop's so
    # request-scoped trace queries see a uniform engine layer —
    # under a serve dispatch the thread-bound trace context stamps
    # the batch's trace_ids onto it.
    span = (tracer.span("engine_segment", "engine",
                        batch_size=len(graphs), n_real=n_real,
                        from_cycle=0, extra_cycles=max_cycles)
            if tracer.active else None)
    with (span if span is not None else contextlib.nullcontext()):
        (values, cycles, stable), compile_s, run_s = timed_jit_call(
            _warm, key,
            functools.partial(_batched_solve, **statics),
            stacked,
        )
    elapsed = time.perf_counter() - t0
    values = np.asarray(jax.device_get(values))[:n_real]
    cycles = np.asarray(jax.device_get(cycles))[:n_real]
    stable = np.asarray(jax.device_get(stable))[:n_real]
    batch_result = DeviceRunResult(
        assignment={},
        cycles=int(cycles.max()) if cycles.size else 0,
        converged=bool(stable.all()) if stable.size else False,
        time_s=elapsed,
        compile_time_s=compile_s,
        metrics={
            "batch_size": len(graphs),
            "n_real": n_real,
            "pad_fraction": pad_fraction,
            "cold_start": compile_s > 0.0,
            "run_time_s": run_s,
            # Per-request convergence verdicts (real lanes, dispatch
            # order): the serve plane folds lane i's flag into
            # request i's result.
            "converged_lanes": [bool(s) for s in stable],
        },
    )
    return values, cycles, batch_result


def solve_maxsum_batch(
    dcops: Sequence[DCOP],
    max_cycles: int = 200,
    noise_level: float = 0.01,
    damping: float = 0.5,
    damping_nodes: str = "both",
    stability: float = 0.1,
    pad_to_bins: Optional[Sequence[int]] = None,
) -> List[Dict]:
    """Solve a batch of same-shaped DCOPs in one vmapped program.

    Returns one dict per instance: assignment, cost (host-evaluated),
    cycles.  All instances run ``max_cycles`` cycles (no convergence
    stop: a data-dependent loop bound would serialize the batch).
    ``pad_to_bins`` pads the stack up a bin-size ladder so a sweep of
    ragged batch sizes reuses a bounded set of compiled programs; the
    shared dispatch accounting (incl. ``pad_fraction``) rides along in
    each result's ``batch`` key.
    """
    if not dcops:
        return []
    # Same-structured instances (same graph, different cost tables —
    # the repeated-traffic serving pattern) are exactly what the
    # structure-keyed compile cache serves: instance 1 builds the
    # layout/agg arrays, instances 2..N reuse them
    # (engine/compile.CompileCache), matching the device side where
    # vmap already made N solves cost barely more than one.
    compiled: List[Tuple[CompiledFactorGraph, FactorGraphMeta]] = [
        compile_dcop(d, noise_level=noise_level) for d in dcops
    ]
    graphs = [c[0] for c in compiled]
    metas = [c[1] for c in compiled]

    values, cycles, batch_result = run_stacked(
        graphs,
        max_cycles=max_cycles,
        damping=damping,
        damping_nodes=damping_nodes,
        stability=stability,
        pad_to_bins=pad_to_bins,
    )

    results = []
    for i, (dcop, meta) in enumerate(zip(dcops, metas)):
        assignment = meta.assignment_from_indices(values[i])
        cost, violations = dcop.solution_cost(assignment)
        results.append({
            "assignment": assignment,
            "cost": cost,
            "violations": violations,
            "cycles": int(cycles[i]),
            "batch": dict(batch_result.metrics),
        })
    return results

"""Dynamic-DCOP execution on the device engine.

The reference handles dynamic problems with agent-level machinery:
scenario events remove agents, replicas re-host their computations
(pydcop/infrastructure/orchestrator.py:955-1178), and maxsum_dynamic
factor computations swap cost functions at runtime
(pydcop/algorithms/maxsum_dynamic.py:40-112 change_factor_function).

On a device engine the graph lives in a handful of dense arrays, so the
dynamic story becomes array surgery (SURVEY §7 "dynamic graphs ...
recompile; mitigate with padding slack and donated buffers"):

- **Padding slack.** Buckets are compiled with spare factor rows
  (`slack` fraction, zero-cost, sentinel var ids).  Adding a factor =
  writing one row; removing = resetting it.  Shapes stay constant, so
  the jitted superstep program is reused — no recompile, no retrace.
- **Warm start.** Message state (MaxSumState) survives every event;
  after an edit the trajectory continues from the previous fixpoint
  (ops/maxsum.py run_maxsum_from) instead of restarting, which is what
  gives cost continuity across events.
- **Recompile fallback.** An edit that outgrows the slack (or needs a
  bigger domain) triggers a recompile with fresh slack; messages of
  surviving factors are copied row-by-row into the new buckets, so even
  the recompile path warm-starts.
- **Placement bookkeeping.** Agent departures do not change the math on
  device (every computation already runs in the same XLA program), but
  ownership matters for reporting parity with the thread runtime: the
  engine keeps a computation->agent map, and `remove_agent` re-homes
  the departed agent's computations onto the least-loaded survivors —
  the device-side analogue of the repair DCOP.
"""

import contextlib
import logging
import math
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

import jax
import numpy as np

from pydcop_tpu.dcop.objects import Variable, _stable_noise
from pydcop_tpu.dcop.relations import Constraint

logger = logging.getLogger("pydcop.engine.dynamic")
from pydcop_tpu.engine.compile import (
    BIG,
    CompiledFactorGraph,
    FactorBucket,
)
from pydcop_tpu.engine.runner import DeviceRunResult, timed_jit_call
from pydcop_tpu.observability import efficiency
from pydcop_tpu.observability.profiler import key_str, profiler
from pydcop_tpu.ops import maxsum as ops


class DynamicMaxSumEngine:
    """MaxSum engine whose factor graph can be edited between runs.

    Always uses the scatter aggregation: the compile-time edge
    structures behind the other strategies (sorted permutations, ell
    lists) would need a rebuild on every graph edit, defeating the
    array-surgery design.  Static solves through
    ``algorithms/maxsum_dynamic.solve_on_device`` delegate to the
    plain engine and do honor ``aggregation``."""

    def __init__(self, variables: List[Variable],
                 constraints: List[Constraint], mode: str = "min",
                 noise_level: float = 0.01,
                 noise_seed: Optional[int] = None,
                 slack: float = 0.25,
                 damping: float = 0.5, damping_nodes: str = "both",
                 stability: float = 0.1, donate: bool = True):
        self.mode = mode
        self.donate = donate
        self.sign = 1.0 if mode == "min" else -1.0
        self.noise_level = noise_level
        self.noise_seed = noise_seed
        self.slack = slack
        self.damping = damping
        self.damp_vars = damping_nodes in ("vars", "both")
        self.damp_factors = damping_nodes in ("factors", "both")
        self.stability = stability

        self.variables: List[Variable] = list(variables)
        self.var_index = {v.name: i for i, v in enumerate(self.variables)}
        # constraint name -> (bucket index, row) for live factors.
        self.slots: Dict[str, Tuple[int, int]] = {}
        self.factors: Dict[str, Constraint] = {}
        self.recompile_count = 0
        # Decimation clamps: variable name -> frozen domain index.  A
        # clamped variable's unary cost row is BIG everywhere except
        # the frozen slot, so the warm-started loop keeps it fixed —
        # data surgery on the var table, never a shape change, so
        # clamping/releasing reuses the compiled superstep program.
        self.clamps: Dict[str, int] = {}
        # Placement bookkeeping (the device-side analogue of the
        # reference's agent hosting): computation name -> agent, plus
        # the set of live agents.  Departures re-home computations onto
        # the least-loaded survivors without touching the device math
        # (every computation already runs in the same XLA program).
        self.placement: Dict[str, Optional[str]] = {}
        self.agents: set = set()
        self._jitted = {}
        self._warm = set()
        self._state = None
        # Cycle counter at the last efficiency record: run() accounts
        # only the cycles THIS call added (the state's counter is
        # cumulative across warm-started runs).
        self._cycles_recorded = 0
        # Efficiency request class this engine's dispatches report.
        # A dynamic engine is not inherently a session — the serve
        # plane's SessionManager relabels the engines it owns; a
        # scenario replay or direct use stays "dynamic".
        self.efficiency_class = "dynamic"
        # Deferred-edit session (batch_edits): None outside a batch.
        self._edit_session = None
        self._build(list(constraints))

    # ------------------------------------------------------------- #
    # compilation / array surgery
    # ------------------------------------------------------------- #

    def _slacked(self, n: int) -> int:
        return max(n + 1, int(math.ceil(n * (1.0 + self.slack))))

    def _build(self, constraints: List[Constraint]):
        """(Re)compile buckets with slack rows; resets slots."""
        self.dmax = max(
            (len(v.domain) for v in self.variables), default=1)
        v_count = len(self.variables)
        var_costs = np.full((v_count + 1, self.dmax), BIG, np.float32)
        var_valid = np.zeros((v_count + 1, self.dmax), bool)
        for i, v in enumerate(self.variables):
            d = len(v.domain)
            var_costs[i, :d] = self._var_base_row(v)
            var_valid[i, :d] = True
        # Clamps survive a recompile: the rebuilt var table starts from
        # base costs, so re-cut the frozen rows (clamps on variables
        # that no longer exist are dropped).
        self.clamps = {
            name: idx for name, idx in self.clamps.items()
            if name in self.var_index
            and idx < len(self.variables[self.var_index[name]].domain)
        }
        for name, idx in self.clamps.items():
            i = self.var_index[name]
            kept = var_costs[i, idx]
            var_costs[i, :] = BIG
            var_costs[i, idx] = kept

        by_arity: Dict[int, List[Constraint]] = {}
        for c in constraints:
            by_arity.setdefault(c.arity, []).append(c)

        buckets = []
        self.slots = {}
        self.factors = {}
        self._free: Dict[int, List[int]] = {}
        for bi, arity in enumerate(sorted(by_arity)):
            facs = by_arity[arity]
            n_rows = self._slacked(len(facs))
            shape = (n_rows,) + (self.dmax,) * arity
            costs = np.zeros(shape, np.float32)
            var_ids = np.full((n_rows, arity), v_count, np.int32)
            for fi, c in enumerate(facs):
                self._write_row(costs, var_ids, fi, c)
                self.slots[c.name] = (bi, fi)
                self.factors[c.name] = c
            self._free[bi] = list(range(len(facs), n_rows))
            buckets.append(FactorBucket(costs, var_ids))
        self._arity_bucket = {
            b.arity: i for i, b in enumerate(buckets)
        }
        self.graph = CompiledFactorGraph(
            var_costs=var_costs, var_valid=var_valid,
            buckets=tuple(buckets),
        )
        self.recompile_count += 1
        self._jitted = {}
        self._warm = set()

    def _write_row(self, costs: np.ndarray, var_ids: np.ndarray,
                   row: int, c: Constraint):
        costs[row], var_ids[row] = self._render_row(
            costs.shape[1:], c)

    def _patch_bucket(self, bi: int, row: int,
                      c: Optional[Constraint]):
        """Replace one bucket row on the host copy and refresh device
        arrays without recompiling (shapes unchanged).  Inside a
        :meth:`batch_edits` session the write is DEFERRED (last write
        per row wins) and materialized with one copy per touched
        bucket at flush — a flattened scenario of N same-bucket
        actions used to copy the whole bucket N times.  The row is
        RENDERED eagerly either way (table evaluation, shape fit,
        scope lookups): a malformed constraint must fail its own
        action — batch-scoped, exactly like the sequential path —
        never the flush, which only assigns pre-built arrays."""
        bucket = self.graph.buckets[bi]
        payload = (None if c is None
                   else self._render_row(bucket.costs.shape[1:], c))
        if self._edit_session is not None:
            self._edit_session["buckets"].setdefault(
                bi, {})[row] = payload
            return
        costs = np.asarray(bucket.costs).copy()
        var_ids = np.asarray(bucket.var_ids).copy()
        self._materialize_bucket_rows(costs, var_ids, {row: payload})
        new_buckets = list(self.graph.buckets)
        new_buckets[bi] = FactorBucket(costs, var_ids)
        self.graph = self.graph._replace(buckets=tuple(new_buckets))

    def _render_row(self, cell_shape, c: Constraint):
        """Evaluate one factor's cost row + scope ids against a
        bucket's cell shape — every way a constraint can be malformed
        (table evaluation, oversize shape, unknown scope variable)
        raises HERE, at action scope."""
        table = self.sign * np.asarray(c.to_array(), np.float32)
        full = np.full(cell_shape, BIG, np.float32)
        idx = tuple(slice(0, s) for s in table.shape)
        full[idx] = table
        ids = np.array([self.var_index[v.name] for v in c.dimensions],
                       np.int32)
        return full, ids

    def _materialize_bucket_rows(self, costs: np.ndarray,
                                 var_ids: np.ndarray, rows: Dict):
        """Assign pre-rendered row payloads onto (already-copied)
        bucket arrays: a ``(costs_row, ids)`` tuple writes a factor,
        None resets the row to slack (zero cost, sentinel ids).
        Assignment-only — cannot fail on malformed input, which is
        what keeps a deferred flush unable to raise mid-batch."""
        for row, payload in rows.items():
            if payload is None:
                costs[row] = 0.0
                var_ids[row] = len(self.variables)
            else:
                costs[row], var_ids[row] = payload

    # -- deferred-edit batching (ISSUE 14 satellite) ---------------- #

    @contextlib.contextmanager
    def batch_edits(self):
        """Accumulate array surgery host-side for the duration of the
        block and materialize ONE copy per touched bucket / var table
        / state array at exit — behavior-identical to the immediate
        path (asserted against the mutation-ladder battery), just
        without the per-action full-bucket copies.  Edits that force
        a recompile (slack exhausted, new variable) flush the pending
        set first, so the rebuild sees exactly the state the
        sequential path would have.  Reentrant: an inner block is a
        no-op, the outermost flushes."""
        if self._edit_session is not None:
            yield self
            return
        self._edit_session = {
            "buckets": {},      # bi -> {row: constraint|None}
            "var_rows": {},     # var index -> row values
            "zero_rows": [],    # (bi, row) state-message resets
        }
        try:
            yield self
        finally:
            # The session clears even if the flush raises: a flush
            # failure must never leave the engine stuck in deferred
            # mode, silently dropping every later edit.
            try:
                self._flush_pending_edits()
            finally:
                self._edit_session = None

    def _flush_pending_edits(self):
        """Materialize the deferred edits in place (one copy per
        touched array).  Leaves the session OPEN but empty — callers
        that must see a consistent graph mid-batch (the recompile
        path) flush and keep accumulating."""
        sess = self._edit_session
        if sess is None:
            return
        bucket_edits, sess["buckets"] = sess["buckets"], {}
        var_rows, sess["var_rows"] = sess["var_rows"], {}
        zero_rows, sess["zero_rows"] = sess["zero_rows"], []
        if bucket_edits:
            new_buckets = list(self.graph.buckets)
            for bi, rows in bucket_edits.items():
                bucket = new_buckets[bi]
                costs = np.asarray(bucket.costs).copy()
                var_ids = np.asarray(bucket.var_ids).copy()
                self._materialize_bucket_rows(costs, var_ids, rows)
                new_buckets[bi] = FactorBucket(costs, var_ids)
            self.graph = self.graph._replace(
                buckets=tuple(new_buckets))
        if var_rows:
            var_costs = np.asarray(self.graph.var_costs).copy()
            for i, row in var_rows.items():
                var_costs[i, :] = row
            self.graph = self.graph._replace(var_costs=var_costs)
        if zero_rows and self._state is not None:
            by_bucket: Dict[int, List[int]] = {}
            for bi, row in zero_rows:
                by_bucket.setdefault(bi, []).append(row)
            self._state = self._zero_state_rows(self._state, by_bucket)

    def _queue_zero_row(self, bi: int, row: int):
        """Neutralize one edge's stale message rows — immediately, or
        deferred into the batch session (one state-array copy per
        touched bucket per batch)."""
        if self._state is None:
            return
        if self._edit_session is not None:
            self._edit_session["zero_rows"].append((bi, row))
            return
        self._state = self._zero_state_rows(self._state, {bi: [row]})

    def _var_base_row(self, v: Variable) -> np.ndarray:
        """The variable's unclamped unary cost slice (sign-folded,
        noise-stabilized) — recomputable at any time because the noise
        is a pure function of the variable name and seed."""
        d = len(v.domain)
        costs = self.sign * v.cost_vector()[:d]
        if self.noise_level:
            costs = costs + _stable_noise(
                v.name, d, self.noise_level, self.noise_seed)
        return np.asarray(costs, np.float32)

    def _patch_var_rows(self, rows: Dict[int, np.ndarray]):
        """Replace unary cost rows on a host copy of the var table and
        refresh the device graph without recompiling (shape
        unchanged).  Deferred under :meth:`batch_edits` — one var
        table copy per batch."""
        if self._edit_session is not None:
            self._edit_session["var_rows"].update(rows)
            return
        var_costs = np.asarray(self.graph.var_costs).copy()
        for i, row in rows.items():
            var_costs[i, :] = row
        self.graph = self.graph._replace(var_costs=var_costs)

    # ------------------------------------------------------------- #
    # decimation clamps
    # ------------------------------------------------------------- #

    def clamp_variables(self, clamps: Dict[str, int]) -> None:
        """Freeze variables at a domain index (decimation clamp): the
        unary row turns BIG everywhere else, so message passing keeps
        the variable pinned while the rest of the graph adapts.  Data
        surgery only — the compiled program is reused."""
        # Validate and build EVERY row before recording anything: a
        # bad entry mid-mapping must not leave earlier names recorded
        # in self.clamps with the var table unpatched (a later
        # recompile would silently start enforcing them).
        rows: Dict[int, np.ndarray] = {}
        validated: Dict[str, int] = {}
        for name, idx in clamps.items():
            i = self.var_index[name]
            v = self.variables[i]
            idx = int(idx)
            if not 0 <= idx < len(v.domain):
                raise ValueError(
                    f"clamp index {idx} out of domain for {name}")
            row = np.full(self.dmax, BIG, np.float32)
            row[idx] = self._var_base_row(v)[idx]
            rows[i] = row
            validated[name] = idx
        if rows:
            self.clamps.update(validated)
            self._patch_var_rows(rows)
            self._unfreeze()

    def release_clamps(self, names: Iterable[str]) -> List[str]:
        """Release decimation clamps on exactly ``names`` (unknown /
        unclamped names are ignored): the base unary rows are
        recomputed and restored, and the warm-started loop is free to
        move those variables again.  Returns the names actually
        released."""
        rows: Dict[int, np.ndarray] = {}
        released = []
        for name in names:
            if name not in self.clamps or name not in self.var_index:
                self.clamps.pop(name, None)
                continue
            del self.clamps[name]
            i = self.var_index[name]
            v = self.variables[i]
            row = np.full(self.dmax, BIG, np.float32)
            row[:len(v.domain)] = self._var_base_row(v)
            rows[i] = row
            released.append(name)
        if rows:
            self._patch_var_rows(rows)
            self._unfreeze()
        return released

    def beliefs(self) -> np.ndarray:
        """Host-side per-variable beliefs ``[V, dmax]``: unary costs
        (clamps included) plus every incident factor->variable
        message.  Before the first run this is just the unary table."""
        bel = np.asarray(
            self.graph.var_costs, np.float64)[:-1].copy()
        if self._state is None:
            return bel
        padded = np.zeros(
            (len(self.variables) + 1, self.dmax), np.float64)
        padded[:-1] = bel
        for bi, bucket in enumerate(self.graph.buckets):
            var_ids = np.asarray(bucket.var_ids).reshape(-1)
            msgs = np.asarray(
                self._state.f2v[bi], np.float64).reshape(
                    -1, self.dmax)
            np.add.at(padded, var_ids, msgs)
        return padded[:-1]

    def decimate(self, margin: float = 0.0,
                 max_fraction: float = 0.25) -> List[str]:
        """Clamp the most-decided unclamped variables to their
        current best value (the Max-Sum decimation discipline): a
        variable qualifies when its belief margin (second best minus
        best over the valid domain) is at least ``margin``; at most
        ``max_fraction`` of the unclamped population clamps per call
        (most-confident first).  Returns the clamped names."""
        bel = self.beliefs()
        valid = np.asarray(self.graph.var_valid)[:-1]
        candidates = []
        for i, v in enumerate(self.variables):
            if v.name in self.clamps:
                continue
            row = np.where(valid[i], bel[i], np.inf)
            if np.count_nonzero(np.isfinite(row)) < 2:
                continue
            order = np.argsort(row)
            m = float(row[order[1]] - row[order[0]])
            if m >= margin:
                candidates.append((m, v.name, int(order[0])))
        if not candidates:
            return []
        budget = max(
            1, int(math.ceil(
                max_fraction
                * (len(self.variables) - len(self.clamps)))))
        candidates.sort(reverse=True)
        chosen = {name: idx for _, name, idx in candidates[:budget]}
        self.clamp_variables(chosen)
        return list(chosen)

    # ------------------------------------------------------------- #
    # placement bookkeeping (agent events)
    # ------------------------------------------------------------- #

    def set_placement(self, mapping: Dict[str, str]) -> None:
        """Computation-name -> agent hosting map (reporting parity
        with the thread runtime; the device math never moves)."""
        self.placement = dict(mapping)
        self.agents = {a for a in mapping.values() if a is not None}

    def add_agent(self, name: str) -> None:
        self.agents.add(name)

    def remove_agent(self, name: str) -> Dict[str, Optional[str]]:
        """Re-home the departed agent's computations onto the
        least-loaded survivors — the device-side analogue of the
        repair DCOP.  With no survivors the computations are orphaned
        (mapped to ``None``) and a warning is logged: the device math
        is unaffected, only the hosting report degrades.  Returns the
        moved computations and their new hosts."""
        self.agents.discard(name)
        moved: Dict[str, Optional[str]] = {}
        loads: Dict[str, int] = {a: 0 for a in self.agents}
        for comp, agent in self.placement.items():
            if agent in loads:
                loads[agent] += 1
        for comp, agent in list(self.placement.items()):
            if agent != name:
                continue
            if loads:
                target = min(loads, key=lambda a: (loads[a], a))
                loads[target] += 1
            else:
                target = None
            self.placement[comp] = target
            moved[comp] = target
        if moved and not self.agents:
            logger.warning(
                "remove_agent(%s): no surviving agents; %d "
                "computation(s) orphaned", name, len(moved))
        return moved

    # ------------------------------------------------------------- #
    # dynamic edits
    # ------------------------------------------------------------- #

    def _unfreeze(self):
        """Every edit clears convergence: the suppression counters and
        the stable flag would otherwise stop the warm-started loop
        before the new costs can propagate."""
        if self._state is not None:
            self._state = self._state._replace(
                stable=np.asarray(False))

    def change_factor(self, name: str, new_constraint: Constraint):
        """Swap a live factor's cost function in place (device
        analogue of maxsum_dynamic change_factor_function).  The edge
        messages survive, so the fixpoint adapts incrementally."""
        if name not in self.slots:
            raise KeyError(f"No live factor named {name}")
        old = self.factors[name]
        # The edge message rows (and their suppression counts) are kept
        # across the swap, so the scope must be IDENTICAL — a factor
        # over different variables would inherit messages computed for
        # the old edges.  Topology changes go through
        # remove_factor + add_factor, which reset the row state.
        if [v.name for v in new_constraint.dimensions] != \
                [v.name for v in old.dimensions]:
            raise ValueError(
                "change_factor requires the same variable scope; use "
                "remove_factor + add_factor for topology changes"
            )
        bi, row = self.slots[name]
        self._patch_bucket(bi, row, new_constraint)
        self.factors[name] = new_constraint
        self._unfreeze()

    def remove_factor(self, name: str):
        """Delete a factor; its row becomes slack.  Messages of other
        edges are untouched (warm start)."""
        bi, row = self.slots.pop(name)
        del self.factors[name]
        self._patch_bucket(bi, row, None)
        self._free[bi].append(row)
        # Stale messages on the removed edge are neutralized: zero rows
        # with sentinel var ids contribute nothing to beliefs.
        self._queue_zero_row(bi, row)

    def add_factor(self, c: Constraint):
        """Insert a factor.  Fits into a slack row when one exists for
        its arity and its domains fit dmax; otherwise triggers a
        recompile with messages carried over."""
        if c.name in self.slots:
            raise ValueError(f"Factor {c.name} already exists")
        new_vars = [
            v for v in c.dimensions if v.name not in self.var_index
        ]
        # New variables grow the var tables (shape change), so the
        # factor cannot take a slack row — register them and fall
        # through to the shared recompile path (one rebuild total).
        # Deferred edits flush FIRST: their slack-row sentinel index
        # is len(self.variables) at queue time, which growing the
        # variable list would silently shift.
        if new_vars:
            self._flush_pending_edits()
        for v in new_vars:
            self.variables.append(v)
            self.var_index[v.name] = len(self.variables) - 1
        bi = self._arity_bucket.get(c.arity)
        fits = (
            not new_vars
            and bi is not None and self._free.get(bi)
            and all(len(v.domain) <= self.dmax for v in c.dimensions)
        )
        self.factors[c.name] = c
        if fits:
            row = self._free[bi].pop(0)
            self._patch_bucket(bi, row, c)
            self.slots[c.name] = (bi, row)
            self._queue_zero_row(bi, row)
        else:
            # A recompile rebuilds arrays and remaps the state by
            # factor name: pending deferred edits must land against
            # the OLD layout first, exactly as the sequential path
            # would have applied them.
            self._flush_pending_edits()
            self._recompile_carrying_messages(
                list(self.factors.values()))

    def add_variable(self, v: Variable):
        """Add a variable (no incident factor yet).  Grows the var
        tables, which changes shapes -> recompile with carry-over."""
        if v.name in self.var_index:
            return
        self._flush_pending_edits()
        self.variables.append(v)
        self.var_index[v.name] = len(self.variables) - 1
        self._recompile_carrying_messages(list(self.factors.values()))

    def _zero_state_rows(self, state: ops.MaxSumState,
                         rows_by_bucket: Dict[int, List[int]]
                         ) -> ops.MaxSumState:
        """Zero message/count rows for a set of edges, ONE array copy
        per touched bucket (the batched form the deferred-edit session
        flushes through; the immediate path passes a single row)."""
        def zero(msgs, fill):
            out = list(msgs)
            for bi, rows in rows_by_bucket.items():
                arr = np.asarray(out[bi]).copy()
                arr[list(rows)] = fill
                out[bi] = arr
            return tuple(out)

        return ops.MaxSumState(
            v2f=zero(state.v2f, 0.0), f2v=zero(state.f2v, 0.0),
            v2f_count=zero(state.v2f_count, 0),
            f2v_count=zero(state.f2v_count, 0),
            stable=np.asarray(False), cycle=np.asarray(state.cycle),
        )

    def _recompile_carrying_messages(self, constraints):
        """Full rebuild; surviving factors' message rows are copied
        into their new slots so the run continues warm."""
        old_state = self._state
        old_slots = dict(self.slots)
        old_graph = self.graph
        self._build(constraints)
        if old_state is None:
            return
        d_old = np.asarray(old_graph.var_costs).shape[1]
        d = self.dmax
        v2f = [np.zeros(b.var_ids.shape + (d,), np.float32)
               for b in self.graph.buckets]
        f2v = [np.zeros(b.var_ids.shape + (d,), np.float32)
               for b in self.graph.buckets]
        v2f_c = [np.zeros(b.var_ids.shape, np.int8)
                 for b in self.graph.buckets]
        f2v_c = [np.zeros(b.var_ids.shape, np.int8)
                 for b in self.graph.buckets]
        old_v2f = [np.asarray(a) for a in old_state.v2f]
        old_f2v = [np.asarray(a) for a in old_state.f2v]
        old_v2f_c = [np.asarray(a) for a in old_state.v2f_count]
        old_f2v_c = [np.asarray(a) for a in old_state.f2v_count]
        dcopy = min(d, d_old)
        for name, (bi, row) in self.slots.items():
            old = old_slots.get(name)
            if old is None:
                continue
            obi, orow = old
            v2f[bi][row, :, :dcopy] = old_v2f[obi][orow, :, :dcopy]
            f2v[bi][row, :, :dcopy] = old_f2v[obi][orow, :, :dcopy]
            v2f_c[bi][row] = old_v2f_c[obi][orow]
            f2v_c[bi][row] = old_f2v_c[obi][orow]
        self._state = ops.MaxSumState(
            v2f=tuple(v2f), f2v=tuple(f2v),
            v2f_count=tuple(v2f_c), f2v_count=tuple(f2v_c),
            stable=np.asarray(False),
            cycle=np.asarray(old_state.cycle),
        )

    # ------------------------------------------------------------- #
    # running
    # ------------------------------------------------------------- #

    def run(self, max_cycles: int = 1000,
            stop_on_convergence: bool = True) -> DeviceRunResult:
        """Continue the trajectory for up to max_cycles more cycles.

        The state argument is donated (``self.donate``, default True):
        across repeated run/edit rounds the superstep program reuses
        the previous round's state buffers in place instead of
        allocating fresh ones.  Host-side array surgery is unaffected
        — the edits rebuild numpy copies, and a donated (device)
        input is only consumed at the next dispatch, after
        ``self._state`` already points at the returned state."""
        key = (max_cycles, stop_on_convergence,
               tuple(b.costs.shape for b in self.graph.buckets),
               self.graph.var_costs.shape)
        if key not in self._jitted:
            import functools

            self._jitted[key] = jax.jit(functools.partial(
                ops.run_maxsum_from,
                extra_cycles=max_cycles,
                damping=self.damping,
                damp_vars=self.damp_vars,
                damp_factors=self.damp_factors,
                stability=self.stability,
                stop_on_convergence=stop_on_convergence,
            ), donate_argnums=(1,) if self.donate else ())
        if self._state is None:
            self._state = ops.init_state(self.graph)
        fn = self._jitted[key]
        (state, values), compile_s, run_s = timed_jit_call(
            self._warm, key, fn, self.graph, self._state)
        self._state = state
        values = np.asarray(jax.device_get(values))
        assignment = {
            v.name: v.domain[int(values[i])]
            for i, v in enumerate(self.variables)
        }
        metrics = {"recompiles": self.recompile_count - 1,
                   "cold_start": compile_s > 0}
        # Efficiency accounting: one warm segment of a long-lived
        # engine is a dispatch like any other — cycles are the delta
        # this call actually ran (the state counter is cumulative).
        ran = max(int(state.cycle) - self._cycles_recorded, 0)
        self._cycles_recorded = int(state.cycle)
        if efficiency.tracker.enabled:
            record = efficiency.tracker.record_dispatch(
                key=str(key),
                structure=efficiency.structure_label(self.graph),
                backend=efficiency.backend_name(),
                time_s=run_s, compile_s=compile_s, cycles=ran,
                n_real=1, batch_size=1,
                packing=self.efficiency_class,
                cost_entry=(profiler.get(key)
                            if profiler.enabled else None),
            )
            if record is not None:
                metrics["efficiency"] = record
        if profiler.enabled:
            entry = profiler.get(key)
            if entry is not None:
                # Superstep programs re-key on bucket shapes, so after
                # a recompile the new program's measured cost appears
                # under its own key.
                metrics["xla_cost"] = {key_str(key): entry}
        return DeviceRunResult(
            assignment=assignment,
            cycles=int(state.cycle),
            converged=bool(state.stable),
            time_s=run_s,
            compile_time_s=compile_s,
            metrics=metrics,
        )

    def cost(self, assignment: Dict) -> float:
        """Host-side solution cost of an assignment: per-variable
        unary costs plus every live factor — the same convention as
        ``DCOP.solution_cost`` (the engine optimizes both, and a
        session's reported cost must be comparable to a one-shot
        ``api.solve``'s)."""
        total = 0.0
        for v in self.variables:
            total += float(v.cost_for_val(assignment[v.name]))
        for c in self.factors.values():
            value = float(c(**{
                v.name: assignment[v.name] for v in c.dimensions
            }))
            # Hard violations contribute 0 to the cost (the
            # solution_cost convention) — an inf total would also be
            # unserializable for the session JSON/SSE surfaces.
            # replay_scenario reports the violation count alongside.
            if abs(value) != float("inf"):
                total += value
        return total

    # ------------------------------------------------------------- #
    # checkpoint / resume
    # ------------------------------------------------------------- #

    def checkpoint(self, path: str) -> None:
        """Dump the solver state to an .npz file.

        The reference has no computation-state checkpointing at all
        (its only resume feature is the batch command's progress file,
        pydcop/commands/batch.py); on device the whole solver state is
        a handful of arrays, so checkpoint/resume is one savez away
        (SURVEY §5 "the TPU build can do better cheaply").  Graph
        layout is NOT saved — restore onto an engine built from the
        same problem (slot names are verified)."""
        if self._state is None:
            raise ValueError("Nothing to checkpoint: engine never ran")
        state = self._state
        names = sorted(self.slots)
        arrays = {
            "cycle": np.asarray(state.cycle),
            "stable": np.asarray(state.stable),
            # Plain unicode dtype (not object): restore() can then load
            # with pickle disabled — checkpoints stay data, not code.
            "slot_names": np.array(names),
            # The (bucket, row) each factor's messages live in: dynamic
            # edits reuse freed rows, so row positions are NOT a pure
            # function of the factor set — restore must remap by name.
            "slot_pos": np.array(
                [self.slots[n] for n in names], dtype=np.int64),
        }
        for bi in range(len(self.graph.buckets)):
            arrays[f"v2f_{bi}"] = np.asarray(state.v2f[bi])
            arrays[f"f2v_{bi}"] = np.asarray(state.f2v[bi])
            arrays[f"v2f_count_{bi}"] = np.asarray(state.v2f_count[bi])
            arrays[f"f2v_count_{bi}"] = np.asarray(state.f2v_count[bi])
        np.savez(path, **arrays)

    def restore(self, path: str) -> None:
        """Load a checkpoint written by :meth:`checkpoint`; the next
        :meth:`run` continues the trajectory from it.  Message rows are
        remapped by factor name (same recipe as
        _recompile_carrying_messages), so the target engine's row
        layout may differ from the checkpointing engine's."""
        data = np.load(path)
        saved_names = [str(n) for n in data["slot_names"]]
        if saved_names != sorted(self.slots):
            only_saved = sorted(set(saved_names) - set(self.slots))
            only_engine = sorted(set(self.slots) - set(saved_names))
            raise ValueError(
                "Checkpoint does not match this engine's factors: "
                f"only in checkpoint {only_saved}, only in engine "
                f"{only_engine}"
            )
        saved_pos = {
            name: tuple(pos)
            for name, pos in zip(saved_names, data["slot_pos"])
        }
        d = self.dmax
        v2f = [np.zeros(b.var_ids.shape + (d,), np.float32)
               for b in self.graph.buckets]
        f2v = [np.zeros(b.var_ids.shape + (d,), np.float32)
               for b in self.graph.buckets]
        v2f_c = [np.zeros(b.var_ids.shape, np.int8)
                 for b in self.graph.buckets]
        f2v_c = [np.zeros(b.var_ids.shape, np.int8)
                 for b in self.graph.buckets]
        for name, (bi, row) in self.slots.items():
            sbi, srow = saved_pos[name]
            saved_row = data[f"v2f_{sbi}"][srow]
            if saved_row.shape != v2f[bi][row].shape:
                raise ValueError(
                    f"Checkpoint row for {name} has shape "
                    f"{saved_row.shape}, engine expects "
                    f"{v2f[bi][row].shape}"
                )
            v2f[bi][row] = saved_row
            f2v[bi][row] = data[f"f2v_{sbi}"][srow]
            v2f_c[bi][row] = data[f"v2f_count_{sbi}"][srow]
            f2v_c[bi][row] = data[f"f2v_count_{sbi}"][srow]
        self._state = ops.MaxSumState(
            v2f=tuple(v2f), f2v=tuple(f2v),
            v2f_count=tuple(v2f_c), f2v_count=tuple(f2v_c),
            stable=np.asarray(bool(data["stable"])),
            cycle=np.asarray(int(data["cycle"]), dtype=np.int32),
        )
        # The efficiency baseline moves with the restored counter:
        # otherwise the first post-restore run() would account every
        # pre-checkpoint cycle as cycles IT ran, inflating attainment.
        self._cycles_recorded = int(data["cycle"])


# --------------------------------------------------------------------- #
# Scenario event vocabulary (dcop/scenario.py actions -> engine edits)
# --------------------------------------------------------------------- #

# Action types the dynamic engine understands.  ``change_factor`` /
# ``add_factor`` / ``remove_factor`` / ``add_variable`` mutate the
# compiled arrays (dcop/scenario.py vocabulary, served by the session
# plane — docs/sessions.md); ``remove_agent`` / ``add_agent`` are the
# reference generator's placement events (generators/scenario_gen.py),
# pure hosting bookkeeping on a device engine.
EVENT_ACTIONS = ("change_factor", "add_factor", "remove_factor",
                 "add_variable", "remove_agent", "add_agent")


def _constraint_from_args(engine: DynamicMaxSumEngine, name: str,
                          args: Dict[str, Any],
                          default_scope: Optional[List[Variable]] = None
                          ) -> Constraint:
    """Build a Constraint from wire/scenario action args: either a
    dense cost ``table`` over ``variables`` (names resolved against
    the engine) or a python ``expression`` (scope inferred from free
    variables).  ``default_scope`` serves change_factor, whose scope
    is the live factor's when the action names none."""
    from pydcop_tpu.dcop.relations import (
        NAryMatrixRelation,
        constraint_from_str,
    )

    if "expression" in args:
        return constraint_from_str(
            name, args["expression"], engine.variables)
    if "table" not in args:
        raise ValueError(
            f"action for factor {name!r} needs a 'table' (dense cost "
            "hypercube) or an 'expression'")
    var_names = args.get("variables")
    if var_names:
        scope = []
        for vn in var_names:
            if vn not in engine.var_index:
                raise ValueError(
                    f"unknown variable {vn!r} in factor {name!r} "
                    "(add_variable it first)")
            scope.append(engine.variables[engine.var_index[vn]])
    elif default_scope is not None:
        scope = list(default_scope)
    else:
        raise ValueError(
            f"factor {name!r} needs a 'variables' list")
    return NAryMatrixRelation(
        scope, np.asarray(args["table"], float), name)


def apply_action(engine: DynamicMaxSumEngine, action_type: str,
                 args: Dict[str, Any]) -> Dict[str, Any]:
    """Apply ONE scenario action to a live engine.

    Returns ``{"type", "touched"}`` where ``touched`` is the variable
    names the edit concerns — exactly the set whose decimation clamps
    the caller should release (clamps elsewhere stay: the event only
    re-opened the touched neighborhood).  Raises ``ValueError`` /
    ``KeyError`` on malformed or unknown actions (the serving front
    end turns these into 400s)."""
    args = dict(args or {})
    if action_type == "change_factor":
        name = args["name"]
        if name not in engine.factors:
            raise KeyError(f"No live factor named {name}")
        old_scope = engine.factors[name].dimensions
        c = _constraint_from_args(engine, name, args,
                                  default_scope=old_scope)
        engine.change_factor(name, c)
        return {"type": action_type,
                "touched": [v.name for v in c.dimensions]}
    if action_type == "add_factor":
        name = args["name"]
        c = _constraint_from_args(engine, name, args)
        engine.add_factor(c)
        return {"type": action_type,
                "touched": [v.name for v in c.dimensions]}
    if action_type == "remove_factor":
        name = args["name"]
        if name not in engine.slots:
            raise KeyError(f"No live factor named {name}")
        touched = [v.name
                   for v in engine.factors[name].dimensions]
        engine.remove_factor(name)
        return {"type": action_type, "touched": touched}
    if action_type == "add_variable":
        from pydcop_tpu.dcop.objects import Domain

        name = args["name"]
        values = args.get("domain")
        if not values:
            raise ValueError(
                f"add_variable {name!r} needs a 'domain' value list")
        engine.add_variable(Variable(
            name, Domain(f"{name}_dom", "", list(values))))
        return {"type": action_type, "touched": [name]}
    if action_type == "remove_agent":
        moved = engine.remove_agent(args["agent"])
        return {"type": action_type, "touched": [],
                "moved": moved}
    if action_type == "add_agent":
        engine.add_agent(args["agent"])
        return {"type": action_type, "touched": []}
    raise ValueError(
        f"unknown scenario action {action_type!r}; valid: "
        f"{', '.join(EVENT_ACTIONS)}")


def build_dynamic_engine(dcop, params: Optional[Dict[str, Any]] = None
                         ) -> DynamicMaxSumEngine:
    """A DynamicMaxSumEngine over a DCOP's variables/constraints with
    the maxsum parameter names the serve plane uses (damping /
    damping_nodes / stability / noise / slack), plus a round-robin
    hosting map over the DCOP's agents so placement events have
    something to move."""
    params = params or {}
    engine = DynamicMaxSumEngine(
        list(dcop.variables.values()),
        list(dcop.constraints.values()),
        mode=dcop.objective,
        noise_level=float(params.get("noise", 0.01)),
        damping=float(params.get("damping", 0.5)),
        damping_nodes=params.get("damping_nodes", "both"),
        stability=float(params.get("stability", 0.1)),
        slack=float(params.get("slack", 0.25)),
    )
    agents = sorted(dcop.agents) or ["a0"]
    comps = ([v.name for v in engine.variables]
             + sorted(engine.factors))
    engine.set_placement({
        comp: agents[i % len(agents)]
        for i, comp in enumerate(comps)
    })
    return engine


def replay_scenario(dcop, scenario,
                    params: Optional[Dict[str, Any]] = None,
                    max_cycles: int = 1000,
                    event_cycles: Optional[int] = None,
                    decimation_margin: Optional[float] = None,
                    on_event=None) -> Dict[str, Any]:
    """Replay a dcop/scenario.py event script through a
    DynamicMaxSumEngine (the ``pydcop solve --scenario`` engine —
    reference-CLI parity for dynamic DCOPs, docs/sessions.md).

    The initial problem is solved to convergence, then each event's
    actions are applied between engine segments (delay events become
    segment boundaries — replay is logical time, not wall clock) and
    the trajectory re-converges WARM from the pre-event fixpoint,
    releasing decimation clamps on the touched variables only.
    Returns the final assignment/cost plus a per-event record
    (actions, recompiles delta, post-event cost/cycles)."""
    engine = build_dynamic_engine(dcop, params)
    budget = event_cycles or max_cycles
    res = engine.run(max_cycles=max_cycles)
    events: List[Dict[str, Any]] = []
    for event in scenario:
        t0 = time.perf_counter()
        if event.is_delay:
            # Logical-time replay: a delay is a chance for the
            # trajectory to settle, not a wall-clock sleep.
            res = engine.run(max_cycles=budget)
            events.append({
                "id": event.id, "delay": event.delay,
                "cost": engine.cost(res.assignment),
                "cycles": res.cycles,
                "recompiles": 0,
                "wall_s": time.perf_counter() - t0,
            })
            continue
        before = engine.recompile_count
        touched: List[str] = []
        applied = []
        for action in (event.actions or []):
            info = apply_action(engine, action.type, action.args)
            touched.extend(info["touched"])
            applied.append(info["type"])
        if touched:
            engine.release_clamps(touched)
        res = engine.run(max_cycles=budget)
        if decimation_margin is not None:
            engine.decimate(margin=decimation_margin)
        rec = {
            "id": event.id,
            "actions": applied,
            "touched": sorted(set(touched)),
            "recompiles": engine.recompile_count - before,
            "cost": engine.cost(res.assignment),
            "cycles": res.cycles,
            "converged": res.converged,
            "wall_s": time.perf_counter() - t0,
        }
        events.append(rec)
        if on_event is not None:
            on_event(rec)
    assignment = res.assignment
    return {
        "assignment": assignment,
        "cost": engine.cost(assignment),
        "cycles": res.cycles,
        "converged": res.converged,
        "events": events,
        "event_count": sum(
            1 for e in scenario if not e.is_delay),
        "recompiles": engine.recompile_count - 1,
        "clamped": sorted(engine.clamps),
        # The factor set the replay ENDED with: consumers comparing
        # against the original problem (violation counting, parity
        # oracles) must know which constraints the events removed.
        "factors": sorted(engine.factors),
        # Hard violations against the LIVE (mutated) factors — a
        # constraint the events removed or replaced no longer binds
        # the solution, so the original problem's tables must not be
        # consulted here.
        "violations": sum(
            1 for c in engine.factors.values()
            if abs(c(**{v.name: assignment[v.name]
                        for v in c.dimensions})) == float("inf")),
        "orphaned": sorted(
            c for c, a in engine.placement.items() if a is None),
    }

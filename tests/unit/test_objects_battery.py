"""Deep battery over dcop/objects.py — domains, the variable family,
agents, and the mass-creation helpers (reference test_dcop_variables.py
depth)."""

import numpy as np
import pytest

from pydcop_tpu.dcop.objects import (
    AgentDef,
    BinaryVariable,
    Domain,
    ExternalVariable,
    Variable,
    VariableDomain,
    VariableNoisyCostFunc,
    VariableWithCostDict,
    VariableWithCostFunc,
    binary_domain,
    create_agents,
    create_binary_variables,
    create_variables,
)
from pydcop_tpu.utils.simple_repr import from_repr, simple_repr

d3 = Domain("d3", "num", [0, 1, 2])


class TestDomain:
    def test_basics(self):
        d = Domain("colors", "color", ["R", "G"])
        assert d.name == "colors"
        assert d.type == "color"
        assert d.domain_type == "color"
        assert len(d) == 2
        assert list(d) == ["R", "G"]
        assert d[1] == "G"
        assert "R" in d and "B" not in d

    def test_index(self):
        assert d3.index(2) == 2
        with pytest.raises(ValueError):
            d3.index(99)

    def test_to_domain_value_exact_and_string(self):
        assert d3.to_domain_value(1) == (1, 1)
        assert d3.to_domain_value("1") == (1, 1)
        with pytest.raises(ValueError, match="not in domain"):
            d3.to_domain_value("9")

    def test_equality_and_hash(self):
        a = Domain("d", "t", [1, 2])
        b = Domain("d", "t", [1, 2])
        c = Domain("d", "t", [2, 1])
        assert a == b and hash(a) == hash(b)
        assert a != c
        assert a != Domain("d", "other", [1, 2])

    def test_values_immutable_tuple(self):
        assert isinstance(d3.values, tuple)

    def test_alias_and_binary_domain(self):
        assert VariableDomain is Domain
        assert list(binary_domain) == [0, 1]

    def test_wire_roundtrip(self):
        d = Domain("d", "t", ["x", "y"])
        assert from_repr(simple_repr(d)) == d


class TestVariable:
    def test_plain(self):
        v = Variable("v", d3)
        assert v.name == "v"
        assert v.domain is d3
        assert v.initial_value is None
        assert v.has_cost is False
        assert v.cost_for_val(2) == 0.0

    def test_domain_from_iterable(self):
        v = Variable("v", [5, 6])
        assert isinstance(v.domain, Domain)
        assert list(v.domain) == [5, 6]

    def test_initial_value_validated(self):
        assert Variable("v", d3, initial_value=2).initial_value == 2
        with pytest.raises(ValueError, match="not in domain"):
            Variable("v", d3, initial_value=9)

    def test_cost_vector_zero(self):
        np.testing.assert_array_equal(
            Variable("v", d3).cost_vector(), [0.0, 0.0, 0.0])

    def test_clone_equal(self):
        v = Variable("v", d3, initial_value=1)
        c = v.clone()
        assert c == v and c is not v
        assert c.initial_value == 1

    def test_equality_is_type_sensitive(self):
        assert Variable("b", binary_domain) != BinaryVariable("b")

    def test_wire_roundtrip(self):
        v = Variable("v", d3, initial_value=2)
        v2 = from_repr(simple_repr(v))
        assert v2 == v and v2.initial_value == 2


class TestCostVariables:
    def test_cost_dict(self):
        v = VariableWithCostDict("v", d3, {0: 1.5, 2: 3.0})
        assert v.has_cost
        assert v.cost_for_val(0) == 1.5
        assert v.cost_for_val(1) == 0.0   # missing -> 0
        np.testing.assert_array_equal(v.cost_vector(), [1.5, 0.0, 3.0])
        assert v.costs == {0: 1.5, 2: 3.0}

    def test_cost_func_callable(self):
        v = VariableWithCostFunc("v", d3, cost_func=lambda x: x * 2)
        assert v.cost_for_val(2) == 4

    def test_cost_func_expression(self):
        v = VariableWithCostFunc("v", d3, cost_func="v * 10")
        assert v.cost_for_val(1) == 10

    def test_cost_func_expression_must_use_own_name(self):
        with pytest.raises(ValueError, match="depend exactly"):
            VariableWithCostFunc("v", d3, cost_func="other + 1")

    def test_cost_func_wire_roundtrip(self):
        v = VariableWithCostFunc("v", d3, cost_func="v * 10")
        v2 = from_repr(simple_repr(v))
        assert v2.cost_for_val(2) == 20

    def test_noisy_cost_deterministic_in_name_and_seed(self):
        a = VariableNoisyCostFunc("v", d3, "v * 1.0", noise_level=0.1,
                                  seed=4)
        b = VariableNoisyCostFunc("v", d3, "v * 1.0", noise_level=0.1,
                                  seed=4)
        c = VariableNoisyCostFunc("v", d3, "v * 1.0", noise_level=0.1,
                                  seed=5)
        assert a.cost_for_val(1) == b.cost_for_val(1)
        assert a.cost_for_val(1) != c.cost_for_val(1)

    def test_noisy_cost_bounded(self):
        v = VariableNoisyCostFunc("v", d3, "v * 1.0", noise_level=0.01)
        for val in d3:
            assert 0 <= v.cost_for_val(val) - float(val) < 0.01
        assert v.noise_level == 0.01

    def test_noisy_clone_same_noise(self):
        v = VariableNoisyCostFunc("v", d3, "v * 1.0", seed=7)
        assert v.clone().cost_for_val(2) == v.cost_for_val(2)

    def test_noisy_wire_roundtrip_preserves_noise(self):
        v = VariableNoisyCostFunc("v", d3, "v * 1.0", noise_level=0.05,
                                  seed=3)
        v2 = from_repr(simple_repr(v))
        assert v2.cost_for_val(1) == v.cost_for_val(1)


class TestBinaryAndExternal:
    def test_binary_variable(self):
        b = BinaryVariable("b")
        assert list(b.domain) == [0, 1]
        assert b.initial_value == 0
        assert b.clone() == b

    def test_external_default_value(self):
        e = ExternalVariable("e", d3)
        assert e.value == 0   # first domain value

    def test_external_set_validates(self):
        e = ExternalVariable("e", d3, value=1)
        with pytest.raises(ValueError, match="not in domain"):
            e.value = 9

    def test_external_fires_callbacks_on_change_only(self):
        e = ExternalVariable("e", d3, value=0)
        seen = []
        e.subscribe(seen.append)
        e.value = 1
        e.value = 1   # unchanged: no event
        e.value = 2
        assert seen == [1, 2]

    def test_external_unsubscribe(self):
        e = ExternalVariable("e", d3)
        seen = []
        e.subscribe(seen.append)
        e.unsubscribe(seen.append)
        e.value = 1
        assert seen == []

    def test_external_wire_roundtrip(self):
        e = ExternalVariable("e", d3, value=2)
        e2 = from_repr(simple_repr(e))
        assert e2.value == 2 and e2.name == "e"


class TestMassCreation:
    def test_create_variables_string_indexes(self):
        vs = create_variables("x_", ["a", "b"], d3)
        assert set(vs) == {"x_a", "x_b"}
        assert vs["x_a"].name == "x_a"

    def test_create_variables_cartesian(self):
        vs = create_variables("x_", [["a", "b"], range(2)], d3)
        assert set(vs) == {("a", 0), ("a", 1), ("b", 0), ("b", 1)}
        assert vs[("b", 1)].name == "x_b_1"

    def test_create_variables_range(self):
        vs = create_variables("v", range(3), d3)
        assert set(vs) == {"v0", "v1", "v2"}

    def test_create_binary_variables(self):
        vs = create_binary_variables("x_", [["c1", "c2"], ["a1"]])
        assert set(vs) == {("c1", "a1"), ("c2", "a1")}
        assert isinstance(vs[("c1", "a1")], BinaryVariable)

    def test_create_agents_range(self):
        ags = create_agents("a", range(2), capacity=42)
        assert set(ags) == {"a0", "a1"}
        assert ags["a0"].capacity == 42


class TestAgentDef:
    def test_defaults(self):
        a = AgentDef("a1")
        assert a.capacity == 100
        assert a.default_hosting_cost == 0
        assert a.default_route == 1
        assert a.hosting_cost("anything") == 0
        assert a.route("a2") == 1

    def test_route_to_self_is_zero(self):
        assert AgentDef("a1").route("a1") == 0

    def test_explicit_costs_and_routes(self):
        a = AgentDef("a1", default_hosting_cost=5,
                     hosting_costs={"c1": 2},
                     default_route=3, routes={"a2": 7})
        assert a.hosting_cost("c1") == 2
        assert a.hosting_cost("c9") == 5
        assert a.route("a2") == 7
        assert a.route("a9") == 3

    def test_extra_attrs_as_attributes(self):
        a = AgentDef("a1", capacity=11, foo="bar")
        assert a.capacity == 11
        assert a.foo == "bar"
        with pytest.raises(AttributeError):
            _ = a.nope

    def test_equality(self):
        assert AgentDef("a1", capacity=5) == AgentDef("a1", capacity=5)
        assert AgentDef("a1", capacity=5) != AgentDef("a1", capacity=6)

    def test_wire_roundtrip_with_extras(self):
        a = AgentDef("a1", capacity=9, hosting_costs={"c": 1.5},
                     routes={"a2": 2.0}, foo="bar")
        a2 = from_repr(simple_repr(a))
        assert a2 == a
        assert a2.foo == "bar"
        assert a2.hosting_cost("c") == 1.5

"""Algorithm plugin-contract tests: typed parameter validation,
defaults injection, and discovery (reference
algorithms/__init__.py:99-566, docs/implementation/algorithms.rst
contract — previously untested here)."""

import pytest

from pydcop_tpu.algorithms import (
    AlgoParameterDef,
    AlgoParameterException,
    AlgorithmDef,
    check_param_value,
    list_available_algorithms,
    load_algorithm_module,
    prepare_algo_params,
)

ALL_14 = [
    "adsa", "amaxsum", "dba", "dpop", "dsa", "dsatuto", "gdba",
    "maxsum", "maxsum_dynamic", "mgm", "mgm2", "mixeddsa", "ncbb",
    "syncbb",
]


class TestCheckParamValue:
    def test_none_returns_default(self):
        p = AlgoParameterDef("damping", "float", None, 0.5)
        assert check_param_value(None, p) == 0.5

    def test_string_coercion_per_type(self):
        assert check_param_value(
            "7", AlgoParameterDef("x", "int", None, 0)) == 7
        assert check_param_value(
            "0.25", AlgoParameterDef("x", "float", None, 0.0)) == 0.25
        assert check_param_value(
            "true", AlgoParameterDef("x", "bool", None, False)) is True
        assert check_param_value(
            "no", AlgoParameterDef("x", "bool", None, True)) is False
        assert check_param_value(
            3, AlgoParameterDef("x", "str", None, "")) == "3"

    def test_invalid_coercion_raises(self):
        with pytest.raises(AlgoParameterException):
            check_param_value(
                "abc", AlgoParameterDef("x", "int", None, 0))
        with pytest.raises(AlgoParameterException):
            check_param_value(
                "abc", AlgoParameterDef("x", "float", None, 0.0))

    def test_allowed_values_enforced(self):
        p = AlgoParameterDef("variant", "str", ["A", "B", "C"], "B")
        assert check_param_value("A", p) == "A"
        with pytest.raises(AlgoParameterException):
            check_param_value("D", p)


class TestPrepareAlgoParams:
    DEFS = [
        AlgoParameterDef("damping", "float", None, 0.5),
        AlgoParameterDef("variant", "str", ["A", "B"], "B"),
    ]

    def test_defaults_filled(self):
        out = prepare_algo_params({}, self.DEFS)
        assert out == {"damping": 0.5, "variant": "B"}

    def test_given_values_validated(self):
        out = prepare_algo_params({"damping": "0.8"}, self.DEFS)
        assert out["damping"] == 0.8

    def test_unknown_param_raises(self):
        with pytest.raises(AlgoParameterException) as exc:
            prepare_algo_params({"dampign": 0.5}, self.DEFS)
        assert "dampign" in str(exc.value)


class TestPluginDiscovery:
    def test_all_14_algorithms_discoverable(self):
        available = list_available_algorithms()
        for algo in ALL_14:
            assert algo in available, algo

    @pytest.mark.parametrize("algo", ALL_14)
    def test_contract_defaults_injected(self, algo):
        """Every module gets algo_params / communication_load /
        computation_memory defaults and declares GRAPH_TYPE."""
        module = load_algorithm_module(algo)
        assert module.GRAPH_TYPE in (
            "factor_graph", "constraints_hypergraph", "pseudotree",
            "ordered_graph",
        )
        assert isinstance(module.algo_params, list)
        assert callable(module.communication_load)
        assert callable(module.computation_memory)

    def test_build_with_default_param_validates(self):
        with pytest.raises(AlgoParameterException):
            AlgorithmDef.build_with_default_param(
                "maxsum", {"no_such_param": 1})
        ad = AlgorithmDef.build_with_default_param(
            "maxsum", {"damping": "0.7"})
        assert ad.params["damping"] == 0.7
        assert ad.params["stability"] > 0  # default filled


class TestAlgorithmDefRepr:
    def test_simple_repr_roundtrip(self):
        from pydcop_tpu.utils.simple_repr import from_repr, simple_repr

        ad = AlgorithmDef.build_with_default_param(
            "dsa", {"variant": "C"})
        clone = from_repr(simple_repr(ad))
        assert clone.algo == "dsa"
        assert clone.params == ad.params
        assert clone.mode == ad.mode
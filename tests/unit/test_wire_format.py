"""Wire-format round-trip tests for every algorithm / infrastructure
message type: simple_repr is the serialization used by the HTTP
transport (multi-process and multi-machine modes), so every message a
computation can post must survive repr -> JSON -> from_repr intact
(reference: SimpleRepr is "the wire format", utils/simple_repr.py).
"""

import json

import numpy as np
import pytest

from pydcop_tpu.utils.simple_repr import from_repr, simple_repr


def roundtrip(msg):
    """repr -> real JSON text -> back (exactly what HTTP does)."""
    wire = json.loads(json.dumps(simple_repr(msg)))
    return from_repr(wire)


class TestAlgorithmMessages:
    def test_maxsum_message(self):
        from pydcop_tpu.infrastructure.agent_algorithms import (
            MaxSumMessage,
        )

        m = MaxSumMessage({"R": 1.5, "G": -0.25, "B": 0.0})
        m2 = roundtrip(m)
        assert m2.costs == m.costs
        assert m2.size == m.size

    @pytest.mark.parametrize("factory_args", [
        ("agent_algorithms", "DsaMessage", ("R",)),
        ("agent_algorithms", "AdsaValueMessage", (2,)),
        ("agent_algorithms", "MgmValueMessage", (1,)),
        ("agent_algorithms", "MgmGainMessage", (3.5, 0.77)),
        ("agent_algorithms", "NcbbValueMessage", ("G",)),
        ("agent_algorithms", "NcbbCostMessage", (12.5, ["v1", "v2"])),
        ("agent_algorithms", "NcbbSearchMessage", ([{"v1": "R"}, {"v1": "G"}],)),
        ("agent_algorithms", "NcbbResultsMessage", ([[{"v1": "R"}, 2.0]],)),
        ("agent_algorithms", "NcbbFinalMessage", ({"v1": "R", "v2": "G"},)),
        ("agent_algorithms", "NcbbStopMessage", ()),
        ("agent_breakout", "DbaOkMessage", ("B",)),
        ("agent_breakout", "DbaEndMessage", ()),
        ("agent_breakout", "GdbaOkMessage", (0,)),
        ("agent_breakout", "GdbaImproveMessage", (2.0,)),
        ("agent_breakout", "MixedDsaMessage", (1,)),
        ("agent_breakout", "Mgm2ValueMessage", ("R",)),
        ("agent_breakout", "Mgm2GainMessage", (4.0,)),
        ("agent_breakout", "Mgm2GoMessage", (True,)),
    ])
    def test_tuple_style_messages(self, factory_args):
        import importlib

        module_name, cls_name, args = factory_args
        module = importlib.import_module(
            f"pydcop_tpu.infrastructure.{module_name}")
        cls = getattr(module, cls_name)
        m = cls(*args)
        m2 = roundtrip(m)
        assert m2 == m

    def test_dpop_util_message_carries_tables(self):
        from pydcop_tpu.dcop.objects import Domain, Variable
        from pydcop_tpu.dcop.relations import NAryMatrixRelation
        from pydcop_tpu.infrastructure.agent_search import (
            DpopUtilMessage,
        )

        d = Domain("d", "", [0, 1])
        x, y = Variable("x", d), Variable("y", d)
        util = NAryMatrixRelation(
            [x, y], np.arange(4).reshape(2, 2).astype(float), "u")
        m2 = roundtrip(DpopUtilMessage(util))
        assert [v.name for v in m2.util.dimensions] == ["x", "y"]
        assert m2.util(1, 0) == util(1, 0)
        assert m2.size == 4

    def test_dpop_value_and_syncbb_messages(self):
        from pydcop_tpu.infrastructure.agent_search import (
            DpopValueMessage,
            SyncBBBackwardMessage,
            SyncBBForwardMessage,
            SyncBBTerminateMessage,
        )

        m = roundtrip(DpopValueMessage({"x": 1, "y": 0}))
        assert m.assignment == {"x": 1, "y": 0}
        fwd = SyncBBForwardMessage(
            [["x", 1], ["y", 0]], 12.0, 20.0, [["x", 1]], 15.0)
        assert roundtrip(fwd) == fwd
        bwd = SyncBBBackwardMessage(20.0, [["x", 1]], 15.0)
        assert roundtrip(bwd) == bwd
        term = SyncBBTerminateMessage({"x": 1}, 15.0)
        assert roundtrip(term) == term

    def test_mgm2_offer_list_survives(self):
        """Offers are (my_value, partner_value, gain) triples; tuples
        come back as lists from JSON, so receivers must get the same
        content in sequence form."""
        from pydcop_tpu.infrastructure.agent_breakout import (
            Mgm2OfferMessage,
        )

        m = Mgm2OfferMessage([(0, 1, 2.5), (1, 0, -1.0)])
        m2 = roundtrip(m)
        normalized = [tuple(o) for o in m2.offers]
        assert normalized == [(0, 1, 2.5), (1, 0, -1.0)]


class TestInfrastructureMessages:
    def test_orchestration_messages(self):
        from pydcop_tpu.infrastructure.orchestratedagents import (
            AgentReadyMessage,
            AgentStoppedMessage,
            ComputationFinishedMessage,
            CycleChangeMessage,
            RemoveComputationsMessage,
            RunAgentMessage,
            StopAgentMessage,
            ValueChangeMessage,
        )

        assert roundtrip(AgentReadyMessage("a1", ["h", 80])) == \
            AgentReadyMessage("a1", ["h", 80])
        assert roundtrip(AgentStoppedMessage("a1", {"cycles": {}})) == \
            AgentStoppedMessage("a1", {"cycles": {}})
        assert roundtrip(ValueChangeMessage("a", "v1", 2, 5, 1.0)) == \
            ValueChangeMessage("a", "v1", 2, 5, 1.0)
        assert roundtrip(CycleChangeMessage("a", "v1", 7)) == \
            CycleChangeMessage("a", "v1", 7)
        assert roundtrip(ComputationFinishedMessage("a", "v1")) == \
            ComputationFinishedMessage("a", "v1")
        assert roundtrip(RunAgentMessage(["v1", "v2"])) == \
            RunAgentMessage(["v1", "v2"])
        assert roundtrip(StopAgentMessage()) == StopAgentMessage()
        assert roundtrip(RemoveComputationsMessage(["x_a"])) == \
            RemoveComputationsMessage(["x_a"])

    def test_deploy_message_ships_computation_def(self):
        """DeployMessage carries a full ComputationDef — the mechanism
        that ships algorithm computations to remote agents."""
        from pydcop_tpu.algorithms import (
            AlgorithmDef,
            ComputationDef,
        )
        from pydcop_tpu.computations_graph import (
            constraints_hypergraph as chg,
        )
        from pydcop_tpu.dcop.objects import Domain, Variable
        from pydcop_tpu.dcop.relations import constraint_from_str
        from pydcop_tpu.infrastructure.orchestratedagents import (
            DeployMessage,
        )

        d = Domain("d", "", [0, 1])
        v0, v1 = Variable("v0", d), Variable("v1", d)
        c = constraint_from_str("c", "v0 + v1", [v0, v1])
        cg = chg.build_computation_graph(
            variables=[v0, v1], constraints=[c])
        algo = AlgorithmDef.build_with_default_param("dsa", mode="min")
        comp_def = ComputationDef(cg.computation("v0"), algo)
        m2 = roundtrip(DeployMessage(comp_def))
        assert m2.comp_def.node.name == "v0"
        assert m2.comp_def.algo.algo == "dsa"
        # The shipped definition is buildable on the receiving side.
        from pydcop_tpu.infrastructure.computations import (
            build_computation,
        )

        comp = build_computation(m2.comp_def)
        assert comp.name == "v0"

    def test_replication_messages(self):
        from pydcop_tpu.replication.dist_ucs_hostingcosts import (
            ActivateReplicaMessage,
            PlaceReplicaMessage,
            UCSProbeMessage,
        )

        assert roundtrip(
            ActivateReplicaMessage("v1", ["a2", "a3"])
        ) == ActivateReplicaMessage("v1", ["a2", "a3"])
        place = PlaceReplicaMessage("v1", None, 2.5, ["a1", "a2"])
        assert roundtrip(place) == place
        probe = UCSProbeMessage("v1", ["a1"], 1.0)
        assert roundtrip(probe) == probe

    def test_discovery_messages(self):
        from pydcop_tpu.infrastructure.discovery import (
            PublishMessage,
            RegisterAgentMessage,
            RegisterComputationMessage,
            SubscribeMessage,
        )

        assert roundtrip(RegisterAgentMessage("a1", ["h", 9001])) == \
            RegisterAgentMessage("a1", ["h", 9001])
        assert roundtrip(
            RegisterComputationMessage("v1", "a1", ["h", 9001])
        ) == RegisterComputationMessage("v1", "a1", ["h", 9001])
        assert roundtrip(SubscribeMessage("agent", "a1", True)) == \
            SubscribeMessage("agent", "a1", True)
        assert roundtrip(PublishMessage("agent_added", "a1", "addr")) \
            == PublishMessage("agent_added", "a1", "addr")
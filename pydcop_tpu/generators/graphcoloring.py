"""Graph-coloring benchmark generator.

Reference parity: pydcop/commands/generators/graphcoloring.py (:238
generate; soft constraints = random 0-9 extensional tables :355; hard
constraints = 1000 on equal colors :378; graphs: random/grid/scalefree
:310-354).
"""

from typing import Optional

import numpy as np

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import (
    NAryMatrixRelation,
    constraint_from_str,
)
from pydcop_tpu.generators import graphs

COLORS = ["R", "G", "B", "O", "F", "Y", "L", "C"]

HARD_PENALTY = 1000


def generate_graph_coloring(
    variables_count: int,
    colors_count: int,
    graph: str = "random",
    soft: bool = False,
    intentional: bool = False,
    p_edge: Optional[float] = None,
    m_edge: Optional[int] = None,
    allow_subgraph: bool = False,
    noagents: bool = False,
    seed: Optional[int] = None,
) -> DCOP:
    rng = np.random.default_rng(seed)
    if colors_count <= len(COLORS):
        colors = COLORS[:colors_count]
    else:
        colors = list(range(colors_count))
    domain = Domain("colors", "color", colors)
    variables = [
        Variable(f"v{i:03d}", domain) for i in range(variables_count)
    ]

    if graph == "random":
        if p_edge is None:
            raise ValueError("random graphs require --p_edge")
        edges = graphs.random_graph(
            variables_count, p_edge, allow_subgraph, seed)
    elif graph == "grid":
        edges = graphs.grid_graph(variables_count)
    elif graph == "scalefree":
        if m_edge is None:
            raise ValueError("scalefree graphs require --m_edge")
        edges = graphs.scalefree_graph(
            variables_count, m_edge, allow_subgraph, seed)
    else:
        raise ValueError(f"Unknown graph type {graph!r}")

    dcop = DCOP(
        f"graph_coloring_{variables_count}_{colors_count}_{graph}",
        objective="min",
    )
    for v in variables:
        dcop.add_variable(v)
    for i, (a, b) in enumerate(edges):
        v1, v2 = variables[a], variables[b]
        name = f"c{i}"
        if soft:
            if intentional:
                raise ValueError(
                    "Soft graph coloring constraints must be extensional"
                )
            table = rng.integers(0, 10, size=(len(domain), len(domain)))
            dcop.add_constraint(NAryMatrixRelation(
                [v1, v2], table.astype(float), name))
        elif intentional:
            dcop.add_constraint(constraint_from_str(
                name,
                f"{HARD_PENALTY} if {v1.name} == {v2.name} else 0",
                [v1, v2],
            ))
        else:
            table = np.zeros((len(domain), len(domain)))
            np.fill_diagonal(table, HARD_PENALTY)
            dcop.add_constraint(NAryMatrixRelation([v1, v2], table, name))

    if not noagents:
        dcop.add_agents([
            AgentDef(f"a{i:03d}", capacity=100)
            for i in range(variables_count)
        ])
    return dcop
